"""Observability subsystem (pilosa_tpu/obs): metrics registry +
Prometheus exposition, the legacy-StatsClient bridge, the expvar
histogram-aggregation fix, distributed tracing (unit + in-process
HTTP), the slow-query endpoint, the runtime collector, and the
tracing-off overhead guard."""

import io
import json
import re

import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.obs import trace as obs_trace
from pilosa_tpu.obs.runtime import RuntimeCollector
from pilosa_tpu.server.handler import Handler
from pilosa_tpu.utils.stats import ExpvarStatsClient, MultiStatsClient


def call(app, method, path, body=b"", content_type="", headers=None):
    if "?" in path:
        path, _, qs = path.partition("?")
    else:
        qs = ""
    environ = {
        "REQUEST_METHOD": method,
        "PATH_INFO": path,
        "QUERY_STRING": qs,
        "CONTENT_LENGTH": str(len(body)),
        "wsgi.input": io.BytesIO(body),
    }
    if content_type:
        environ["CONTENT_TYPE"] = content_type
    for k, v in (headers or {}).items():
        environ["HTTP_" + k.upper().replace("-", "_")] = v
    out = {}

    def start_response(status, hs):
        out["status"] = int(status.split()[0])
        out["headers"] = dict(hs)

    chunks = app(environ, start_response)
    return out["status"], out["headers"], b"".join(chunks)


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def handler(holder):
    ex = Executor(holder, host="local", use_mesh=False)
    yield Handler(holder, ex, host="local")
    ex.close()


# -- Prometheus text-exposition parser (the validity check) ------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="     # labels: name=
    r"\"(?:[^\"\\]|\\.)*\",?)*)\})?"        # "escaped value"
    r" (NaN|[-+]?(?:[0-9.eE+-]+|Inf))$")    # value


def parse_exposition(text: str) -> dict:
    """Strict-enough parser for the Prometheus text format 0.0.4:
    every non-comment line must be ``name{labels} value``; TYPE lines
    must precede their family's samples. Returns {family: {"type":
    ..., "samples": [(name, labels-dict, value-str)]}}."""
    families: dict = {}
    typed: dict = {}
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, typ = rest.split()
            assert typ in ("counter", "gauge", "histogram", "summary",
                           "untyped")
            typed[name] = typ
            families.setdefault(name, {"type": typ, "samples": []})
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name, rawlabels, value = m.group(1), m.group(2), m.group(3)
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        fam = base if base in typed else name
        assert fam in typed, f"sample {name} precedes its TYPE line"
        labels = dict(re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="'
                                 r'((?:[^"\\]|\\.)*)"', rawlabels or ""))
        families[fam]["samples"].append((name, labels, value))
    return families


class TestExpositionEscaping:
    # The exposition-spec escape matrix: label VALUES escape
    # backslash, double quote, and line feed; HELP text escapes ONLY
    # backslash and line feed (an escaped quote in help is itself a
    # spec violation strict OpenMetrics parsers reject).

    HOSTILE = ('back\\slash', 'quo"te', 'new\nline',
               'all\\three" at\nonce', 'trailing\\', '\\"')

    @staticmethod
    def _unescape(v: str) -> str:
        from pilosa_tpu.obs.federate import unescape_label_value
        return unescape_label_value(v)

    def test_hostile_label_values_round_trip(self):
        """Hostile label values render escaped and parse back to the
        exact original through the existing test parser."""
        reg = obs_metrics.Registry()
        c = reg.counter("pilosa_test_hostile_events_total",
                        labels=("k",))
        for v in self.HOSTILE:
            c.labels(v).inc()
        text = reg.render()
        # Every rendered line must stay single-line (the newline in
        # the value is escaped, not emitted).
        for line in text.splitlines():
            assert "\n" not in line
        fams = parse_exposition(text)
        got = {self._unescape(labels["k"])
               for _n, labels, _v in
               fams["pilosa_test_hostile_events_total"]["samples"]}
        assert got == set(self.HOSTILE), got
        # The OpenMetrics rendering escapes identically (parsed with
        # the production federation parser, which unescapes — the
        # 0.0.4 test parser above is strict about OM counter naming).
        from pilosa_tpu.obs import federate
        om = reg.render(openmetrics=True)
        got_om = {labels["k"] for _n, labels, _v in
                  federate.parse_exposition(om)[
                      "pilosa_test_hostile_events_total"]["samples"]}
        assert got_om == set(self.HOSTILE), got_om

    def test_help_escapes_backslash_newline_but_not_quote(self):
        reg = obs_metrics.Registry()
        reg.counter("pilosa_test_help_events_total",
                    'say "hi" to\na back\\slash')
        text = reg.render()
        help_line = next(ln for ln in text.splitlines()
                         if ln.startswith("# HELP"))
        # Quote NOT escaped; newline and backslash escaped.
        assert 'say "hi" to\\na back\\\\slash' in help_line, help_line
        assert '\\"' not in help_line

    def test_federate_parser_matches_test_parser(self):
        """The production exposition parser (obs.federate — the one
        /metrics/cluster merges through) agrees with this test file's
        parser on hostile values, unescaping included."""
        from pilosa_tpu.obs import federate
        reg = obs_metrics.Registry()
        c = reg.counter("pilosa_test_cross_events_total",
                        labels=("k",))
        for v in self.HOSTILE:
            c.labels(v).inc(2)
        fams = federate.parse_exposition(reg.render())
        got = {labels["k"]: v for _n, labels, v in
               fams["pilosa_test_cross_events_total"]["samples"]}
        assert set(got) == set(self.HOSTILE)
        assert all(v == 2.0 for v in got.values())


class TestRegistry:
    def test_counter_gauge_histogram_render(self):
        reg = obs_metrics.Registry()
        c = reg.counter("pilosa_test_widgets_total", "w", labels=("k",))
        c.labels("a").inc()
        c.labels("a").inc(2)
        g = reg.gauge("pilosa_test_queue_depth")
        g.set(7)
        h = reg.histogram("pilosa_test_latency_seconds",
                          buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(30.0)
        fams = parse_exposition(reg.render())
        (name, labels, value), = fams["pilosa_test_widgets_total"][
            "samples"]
        assert labels == {"k": "a"} and value == "3"
        assert fams["pilosa_test_queue_depth"]["samples"][0][2] == "7"
        hs = {(n, ls.get("le")): v for n, ls, v in
              fams["pilosa_test_latency_seconds"]["samples"]}
        assert hs[("pilosa_test_latency_seconds_bucket", "0.1")] == "1"
        assert hs[("pilosa_test_latency_seconds_bucket", "1")] == "2"
        assert hs[("pilosa_test_latency_seconds_bucket", "+Inf")] == "3"
        assert hs[("pilosa_test_latency_seconds_count", None)] == "3"

    def test_naming_convention_enforced_at_registration(self):
        reg = obs_metrics.Registry()
        with pytest.raises(ValueError):
            reg.counter("pilosa_bad_total")  # too few segments
        with pytest.raises(ValueError):
            reg.counter("pilosa_test_widgets_count")  # not _total
        with pytest.raises(ValueError):
            reg.gauge("queue_depth_things")  # no pilosa prefix
        with pytest.raises(ValueError):
            reg.gauge("pilosa_Bad_Case_value")

    def test_reregistration_returns_same_family(self):
        reg = obs_metrics.Registry()
        a = reg.counter("pilosa_test_events_total", labels=("k",))
        b = reg.counter("pilosa_test_events_total", labels=("k",))
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("pilosa_test_events_total")

    def test_stats_bridge_feeds_registry(self):
        reg = obs_metrics.Registry()
        bridge = obs_metrics.RegistryStatsClient(reg)
        bridge.count("slowQueries", 3)
        bridge.gauge("indexN", 2)
        bridge.timing("snapshotDurationNs", 2_500_000)  # 2.5 ms
        tagged = bridge.with_tags("index:i")
        tagged.count("setN", 5)
        fams = parse_exposition(reg.render())
        assert fams["pilosa_stats_slow_queries_total"]["samples"][0][2] \
            == "3"
        assert fams["pilosa_stats_index_n_value"]["samples"][0][2] == "2"
        # timing lands as a seconds histogram, ns stripped
        samples = fams["pilosa_stats_snapshot_duration_seconds"][
            "samples"]
        assert any(n.endswith("_count") and v == "1"
                   for n, _, v in samples)
        set_samples = fams["pilosa_stats_set_n_total"]["samples"]
        assert set_samples[0][1]["tags"] == "index:i"

    def test_declared_set_is_importable_and_nonempty(self):
        fams = obs_metrics.default_registry().families()
        assert "pilosa_query_duration_seconds" in fams
        assert "pilosa_compile_cache_misses_total" in fams


class TestExpvarHistogramAggregation:
    def test_histogram_aggregates_not_last_write_wins(self):
        c = ExpvarStatsClient()
        for v in (5.0, 1.0, 9.0):
            c.histogram("lat", v)
        snap = c.snapshot()["lat"]
        assert snap == {"count": 3, "sum": 15.0, "min": 1.0,
                        "max": 9.0, "last": 9.0}

    def test_timing_same_semantics(self):
        c = ExpvarStatsClient()
        c.timing("t", 100.0)
        c.timing("t", 300.0)
        snap = c.snapshot()["t"]
        assert snap["count"] == 2 and snap["sum"] == 400.0

    def test_snapshot_copies_do_not_tear(self):
        c = ExpvarStatsClient()
        c.histogram("h", 1.0)
        snap = c.snapshot()
        c.histogram("h", 2.0)
        assert snap["h"]["count"] == 1  # not a live reference

    def test_multi_snapshot_merges_children(self):
        a, b = ExpvarStatsClient(), ExpvarStatsClient()
        a.count("x", 1)
        b.count("y", 2)
        multi = MultiStatsClient([a, b])
        snap = multi.snapshot()
        assert snap["x"] == 1 and snap["y"] == 2


class TestMetricsEndpoint:
    def test_metrics_valid_and_has_query_latency(self, handler, holder):
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        status, _, _ = call(
            handler, "POST", "/index/i/query",
            b'SetBit(frame="f", rowID=1, columnID=10)')
        assert status == 200
        status, _, _ = call(handler, "POST", "/index/i/query",
                            b'Count(Bitmap(frame="f", rowID=1))')
        assert status == 200
        status, headers, body = call(handler, "GET", "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        fams = parse_exposition(body.decode())
        lat = fams["pilosa_query_duration_seconds"]
        assert lat["type"] == "histogram"
        counts = [(ls, v) for n, ls, v in lat["samples"]
                  if n.endswith("_count")]
        by_call = {(ls["call"], ls["lane"], ls["status"]): v
                   for ls, v in counts}
        assert int(by_call[("Count", "read", "200")]) >= 1
        assert int(by_call[("SetBit", "write", "200")]) >= 1

    def test_import_counter(self, handler, holder):
        import numpy as np
        from pilosa_tpu.proto import internal_pb2 as pb
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        before = obs_metrics.IMPORT_BITS.labels("bits").value
        req = pb.ImportRequest(Index="i", Frame="f", Slice=0,
                               RowIDs=[1, 1], ColumnIDs=[3, 4])
        status, _, _ = call(handler, "POST", "/import",
                            req.SerializeToString(),
                            content_type="application/x-protobuf",
                            headers={"Accept":
                                     "application/x-protobuf"})
        assert status == 200
        assert obs_metrics.IMPORT_BITS.labels("bits").value \
            == before + 2
        assert np is not None


class TestSlowQueryEndpoint:
    def test_slow_log_over_http(self, holder):
        from pilosa_tpu.sched import QueryRegistry
        ex = Executor(holder, host="local", use_mesh=False)
        registry = QueryRegistry(slow_threshold_s=0.0 + 1e-9)
        h = Handler(holder, ex, host="local", registry=registry)
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        status, headers, _ = call(h, "POST", "/index/i/query",
                                  b'Count(Bitmap(frame="f", rowID=1))')
        assert status == 200
        qid = headers["X-Pilosa-Query-Id"]
        status, _, body = call(h, "GET", "/debug/queries/slow")
        assert status == 200
        entries = json.loads(body)["slow"]
        assert entries and entries[-1]["id"] == qid
        assert "execute" in entries[-1]["stages"]
        ex.close()


class TestTracing:
    def test_per_request_opt_in_records_spans(self, handler, holder):
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        status, headers, _ = call(
            handler, "POST", "/index/i/query?trace=1",
            b'Count(Bitmap(frame="f", rowID=1))')
        assert status == 200
        qid = headers["X-Pilosa-Query-Id"]
        status, _, body = call(handler, "GET", "/debug/traces")
        listing = json.loads(body)
        assert [t for t in listing["traces"] if t["id"] == qid]
        status, _, body = call(handler, "GET", f"/debug/traces/{qid}")
        assert status == 200
        chrome = json.loads(body)
        names = {e["name"] for e in chrome["traceEvents"]}
        # parse → admission → execute (map_reduce + local leg + merge)
        # → encode, plus the perfetto process-name metadata.
        assert {"parse", "admission", "execute", "map_reduce", "leg",
                "merge", "encode", "process_name"} <= names
        for e in chrome["traceEvents"]:
            if e["name"] != "process_name":
                assert e["ph"] == "X" and e["dur"] >= 1
        assert chrome["otherData"]["traceId"] == qid

    def test_trace_404_and_listing_shape(self, handler):
        status, _, _ = call(handler, "GET", "/debug/traces/nope")
        assert status == 404
        status, _, body = call(handler, "GET", "/debug/traces")
        assert status == 200
        assert json.loads(body)["enabled"] is False

    def test_remote_leg_piggybacks_spans_header(self, holder):
        """A remote (forwarded) query that carries X-Pilosa-Trace
        returns its spans in the response header — the stitching
        contract the cluster client consumes."""
        ex = Executor(holder, host="local", use_mesh=False)
        h = Handler(holder, ex, host="local")
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        from pilosa_tpu.server import codec
        body = codec.encode_query_request(
            'Count(Bitmap(frame="f", rowID=1))', [0], remote=True)
        status, headers, _ = call(
            h, "POST", "/index/i/query", body,
            content_type="application/x-protobuf",
            headers={"X-Pilosa-Trace": "1",
                     "X-Pilosa-Query-Id": "stitchme",
                     "Accept": "application/x-protobuf"})
        assert status == 200
        spans = json.loads(headers[obs_trace.SPANS_HEADER])
        names = {row[0] for row in spans}
        assert "execute" in names and "map_reduce" in names
        assert headers["X-Pilosa-Query-Id"] == "stitchme"
        ex.close()

    def test_stitched_remote_spans_merge_into_trace(self):
        trace = obs_trace.Trace("q1", node="coord")
        remote = obs_trace.Trace("q1", node="peer")
        remote.add_span("execute", 100.0, 0.5)
        trace.add_span("rpc", 99.9, 0.7)
        trace.add_remote_json(remote.spans_json())
        spans = trace.spans()
        assert {s.node for s in spans} == {"coord", "peer"}
        chrome = trace.to_chrome()
        procs = {e["args"]["name"] for e in chrome["traceEvents"]
                 if e["name"] == "process_name"}
        assert procs == {"coord", "peer"}

    def test_spans_json_respects_wire_budget(self):
        """The piggyback header must stay under http.client's 64 KB
        header-line limit no matter how many spans a leg recorded —
        over budget, the newest spans drop, never the parse/admission
        prefix."""
        trace = obs_trace.Trace("q", node="n" * 40)
        for i in range(obs_trace.MAX_SPANS):
            trace.add_span(f"span_{i}", float(i), 0.5,
                           tags={"detail": "x" * 80})
        wire = trace.spans_json()
        assert len(wire) <= obs_trace.Trace._WIRE_BYTES
        rows = json.loads(wire)
        assert rows and rows[0][0] == "span_0"  # prefix kept
        # And a small trace round-trips untruncated.
        small = obs_trace.Trace("q2")
        small.add_span("a", 1.0, 0.1)
        assert len(json.loads(small.spans_json())) == 1

    def test_span_cap_drops_not_grows(self):
        trace = obs_trace.Trace("q", max_spans=4)
        for i in range(10):
            trace.add_span(f"s{i}", 0.0, 0.1)
        assert len(trace.spans()) == 4
        assert trace.dropped == 6
        assert trace.summary()["dropped"] == 6


class TestOverheadGuard:
    def test_tracing_off_is_default_and_allocates_no_spans(
            self, handler, holder, monkeypatch):
        """With tracing at defaults a query must not construct a
        single Span object, and nothing lands in the trace ring."""
        from pilosa_tpu.utils.config import TraceConfig
        assert TraceConfig().enabled is False
        assert handler.tracer.enabled is False

        made = []
        real = obs_trace.Span

        class CountingSpan(real):
            def __init__(self, *a, **kw):
                made.append(1)
                super().__init__(*a, **kw)

        monkeypatch.setattr(obs_trace, "Span", CountingSpan)
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        status, _, _ = call(handler, "POST", "/index/i/query",
                            b'Count(Bitmap(frame="f", rowID=1))')
        assert status == 200
        assert made == []
        assert handler.tracer.traces() == []

    def test_span_current_nop_fast_path(self):
        assert obs_trace.span_current("x") is obs_trace.NOP_SPAN
        from pilosa_tpu.sched import QueryContext
        from pilosa_tpu.sched import context as sched_context
        ctx = QueryContext(pql="q")  # no trace attached
        with sched_context.use(ctx):
            assert obs_trace.span_current("x") is obs_trace.NOP_SPAN


class TestRuntimeCollector:
    def test_collect_shapes(self, holder):
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f").set_bit("standard", 1, 2)
        rc = RuntimeCollector(holder=holder)
        snap = rc.collect()
        assert snap["holder"]["indexes"] == 1
        assert snap["holder"]["fragments"] >= 1
        assert snap["threads"]["live"] >= 1
        assert {"hits", "misses", "programs"} <= set(
            snap["compileCache"])
        assert rc.snapshot() is not None

    def test_compile_stats_count_builds(self):
        from pilosa_tpu.parallel import mesh as mesh_mod
        before = mesh_mod.compile_stats()
        mesh = mesh_mod.make_mesh()
        import numpy as np
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        slab = mesh_mod.shard_slices(
            mesh, np.zeros((n_dev, 64), np.uint32))
        # An uncommon expr shape forces a fresh program build + first
        # call; a repeat of the same call must be a pure cache hit.
        expr = ("or", ("and", ("leaf", 0), ("leaf", 1)),
                ("andnot", ("leaf", 1), ("leaf", 0)))
        mesh_mod.count_expr_sharded(mesh, expr, [slab, slab])
        mid = mesh_mod.compile_stats()
        assert mid["programsBuilt"] > before["programsBuilt"]
        assert mid["firstCalls"] > before["firstCalls"]
        assert mid["compileSeconds"] > before["compileSeconds"]
        mesh_mod.count_expr_sharded(mesh, expr, [slab, slab])
        after = mesh_mod.compile_stats()
        assert after["programsBuilt"] == mid["programsBuilt"]
        assert after["hits"] > mid["hits"]

    def test_roaring_op_counts(self):
        from pilosa_tpu.storage import roaring
        before = roaring.op_counts()
        a = roaring.Bitmap(1, 2, 3)
        b = roaring.Bitmap(2, 3, 4)
        a.intersect(b)
        after = roaring.op_counts()
        key = ("intersect", "array_array")
        assert after[key] == before[key] + 1
