"""Generic iterator tests, mirroring the reference's iterator semantics
(iterator.go): seek-to-next-pair, one-deep unread, limit EOF, and the
roaring position adaptor."""

from __future__ import annotations

import numpy as np
import pytest

from pilosa_tpu.storage.iterators import (
    SLICE_WIDTH,
    BufIterator,
    LimitIterator,
    RoaringIterator,
    SliceIterator,
    pairs,
)
from pilosa_tpu.storage.roaring import Bitmap


def make_slice_iter():
    return SliceIterator(np.array([1, 1, 2, 5], dtype=np.uint64),
                         np.array([3, 9, 0, 7], dtype=np.uint64))


def test_slice_iterator_drains_in_order():
    assert pairs(make_slice_iter()) == [(1, 3), (1, 9), (2, 0), (5, 7)]


def test_slice_iterator_length_mismatch():
    with pytest.raises(ValueError):
        SliceIterator([1], [2, 3])


def test_slice_iterator_seek_exact_and_next_pair():
    itr = make_slice_iter()
    itr.seek(1, 9)                       # exact pair
    assert itr.next() == (1, 9, False)
    itr.seek(1, 10)                      # between pairs → next greater
    assert itr.next() == (2, 0, False)
    itr.seek(9, 0)                       # beyond all → EOF
    assert itr.next() == (0, 0, True)


def test_buf_iterator_unread_and_peek():
    itr = BufIterator(make_slice_iter())
    assert itr.next() == (1, 3, False)
    itr.unread()
    assert itr.next() == (1, 3, False)   # replays the buffered pair
    assert itr.peek() == (1, 9, False)   # peek does not consume
    assert itr.next() == (1, 9, False)


def test_buf_iterator_double_unread_errors():
    itr = BufIterator(make_slice_iter())
    itr.next()
    itr.unread()
    with pytest.raises(RuntimeError):
        itr.unread()


def test_buf_iterator_seek_clears_buffer():
    itr = BufIterator(make_slice_iter())
    itr.next()
    itr.unread()
    itr.seek(2, 0)
    assert itr.next() == (2, 0, False)


def test_limit_iterator_eof_past_max_pair():
    itr = LimitIterator(make_slice_iter(), 2, 0)
    assert pairs(itr) == [(1, 3), (1, 9), (2, 0)]
    assert itr.next() == (0, 0, True)    # stays EOF (iterator.go:105-108)


def test_limit_iterator_seek_revives_after_eof():
    itr = LimitIterator(make_slice_iter(), 2, 0)
    pairs(itr)                           # drain past the limit
    itr.seek(1, 0)
    assert itr.next() == (1, 3, False)


def test_buf_iterator_unread_before_next_errors():
    itr = BufIterator(make_slice_iter())
    with pytest.raises(RuntimeError):
        itr.unread()


def test_limit_iterator_row_boundary():
    itr = LimitIterator(make_slice_iter(), 1, 1 << 62)
    assert pairs(itr) == [(1, 3), (1, 9)]


def test_roaring_iterator_position_mapping():
    bm = Bitmap()
    positions = [5, SLICE_WIDTH + 7, 3 * SLICE_WIDTH]
    for p in positions:
        bm.add(p)
    assert pairs(RoaringIterator(bm)) == [(0, 5), (1, 7), (3, 0)]


def test_roaring_iterator_seek():
    bm = Bitmap()
    for p in (5, SLICE_WIDTH + 7, 3 * SLICE_WIDTH):
        bm.add(p)
    itr = RoaringIterator(bm)
    itr.seek(1, 0)
    assert itr.next() == (1, 7, False)
    itr.seek(1, 8)                       # past row 1's only bit
    assert itr.next() == (3, 0, False)


def test_composition_buf_over_limit_over_roaring():
    bm = Bitmap()
    for p in (1, 2, SLICE_WIDTH + 1, 2 * SLICE_WIDTH + 9):
        bm.add(p)
    itr = BufIterator(LimitIterator(RoaringIterator(bm), 1, 1 << 62))
    assert itr.peek() == (0, 1, False)
    assert pairs(itr) == [(0, 1), (0, 2), (1, 1)]
