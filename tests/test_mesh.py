"""Device-mesh slice executor tests on the 8-device virtual CPU mesh
(conftest.py sets xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

from pilosa_tpu.parallel import mesh as mesh_mod


def _popcount(arr: np.ndarray) -> int:
    return int(np.unpackbits(arr.view(np.uint8)).sum())


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


class TestMakeMesh:
    def test_shapes(self):
        m = mesh_mod.make_mesh(8)
        assert m.devices.shape == (1, 8)
        m2 = mesh_mod.make_mesh(8, rows=2)
        assert m2.devices.shape == (2, 4)

    def test_too_many_devices(self):
        with pytest.raises(ValueError):
            mesh_mod.make_mesh(512)


class TestCountOp:
    @pytest.mark.parametrize("op,npop", [
        ("and", np.bitwise_and),
        ("or", np.bitwise_or),
        ("xor", np.bitwise_xor),
        ("andnot", lambda a, b: np.bitwise_and(a, np.bitwise_not(b))),
    ])
    def test_matches_numpy(self, rng, op, npop):
        m = mesh_mod.make_mesh(8)
        a = rng.integers(0, 2**32, size=(16, 512), dtype=np.uint32)
        b = rng.integers(0, 2**32, size=(16, 512), dtype=np.uint32)
        got = mesh_mod.count_op(m, op, mesh_mod.shard_slices(m, a),
                                mesh_mod.shard_slices(m, b))
        assert got == _popcount(npop(a, b))

    def test_zero_padding_is_identity(self, rng):
        m = mesh_mod.make_mesh(8)
        a = rng.integers(0, 2**32, size=(5, 256), dtype=np.uint32)
        b = rng.integers(0, 2**32, size=(5, 256), dtype=np.uint32)
        ap = mesh_mod.pad_to_multiple(a, 8)
        bp = mesh_mod.pad_to_multiple(b, 8)
        assert ap.shape[0] == 8
        got = mesh_mod.count_op(m, "and", mesh_mod.shard_slices(m, ap),
                                mesh_mod.shard_slices(m, bp))
        assert got == _popcount(np.bitwise_and(a, b))


class TestTopN:
    def test_matches_numpy(self, rng):
        m = mesh_mod.make_mesh(8, rows=2)   # 2×4 grid: both axes real
        S, R, W = 8, 16, 128
        rows = rng.integers(0, 2**32, size=(S, R, W), dtype=np.uint32)
        src = rng.integers(0, 2**32, size=(S, W), dtype=np.uint32)
        vals, ids = mesh_mod.topn_counts(
            m, "and",
            mesh_mod.shard_slices(m, rows), mesh_mod.shard_slices(m, src),
            k=4)
        want = np.array([
            _popcount(np.bitwise_and(rows[:, r, :], src))
            for r in range(R)])
        order = np.argsort(-want, kind="stable")
        assert list(vals) == list(want[order][:4])
        # ids must be a valid argmax set (ties may reorder).
        assert sorted(want[ids]) == sorted(vals)


class TestQueryStep:
    def test_fused_step(self, rng):
        m = mesh_mod.make_mesh(8, rows=2)
        S, R, W = 8, 8, 128
        a = rng.integers(0, 2**32, size=(S, W), dtype=np.uint32)
        b = rng.integers(0, 2**32, size=(S, W), dtype=np.uint32)
        rows = rng.integers(0, 2**32, size=(S, R, W), dtype=np.uint32)
        n_i, n_u, vals, ids = mesh_mod.query_step(
            m, mesh_mod.shard_slices(m, a), mesh_mod.shard_slices(m, b),
            mesh_mod.shard_slices(m, rows), k=3)
        inter = np.bitwise_and(a, b)
        assert n_i == _popcount(inter)
        assert n_u == _popcount(np.bitwise_or(a, b))
        want = np.array([
            _popcount(np.bitwise_and(rows[:, r, :], inter))
            for r in range(R)])
        assert list(vals) == sorted(want, reverse=True)[:3]


class TestCompileCache:
    def test_arm_respects_disable_and_override(self, monkeypatch,
                                               tmp_path):
        from pilosa_tpu.parallel import mesh as mesh_mod
        import jax
        prior_dir = jax.config.jax_compilation_cache_dir
        prior_min = jax.config.jax_persistent_cache_min_compile_time_secs
        try:
            # disabled: config untouched
            monkeypatch.setattr(mesh_mod, "_compile_cache_armed", False)
            monkeypatch.setenv("PILOSA_TPU_COMPILE_CACHE", "0")
            mesh_mod._arm_compile_cache()
            assert (jax.config.jax_compilation_cache_dir
                    == prior_dir)
            # explicit dir: set + created (even off-TPU — explicit
            # opt-in overrides the platform gate)
            monkeypatch.setattr(mesh_mod, "_compile_cache_armed", False)
            target = str(tmp_path / "xla")
            monkeypatch.setenv("PILOSA_TPU_COMPILE_CACHE", target)
            mesh_mod._arm_compile_cache()
            assert jax.config.jax_compilation_cache_dir == target
            import os
            assert os.path.isdir(target)
            # idempotent: second call is a no-op even with env changed
            monkeypatch.setenv("PILOSA_TPU_COMPILE_CACHE", "0")
            mesh_mod._arm_compile_cache()
            assert jax.config.jax_compilation_cache_dir == target
        finally:
            # jax.config is process-global: restore so later tests are
            # order-independent (review finding).
            jax.config.update("jax_compilation_cache_dir", prior_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                prior_min)
