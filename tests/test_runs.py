"""Run containers (ISSUE 7): the third container type end-to-end.

Randomized differential legs hold the algebra to the pure-python set
model bit-for-bit across every operand-kind pair, the serialization
legs prove the 12347 runs cookie round-trips through snapshot + WAL
replay + mmap + the fragment lifecycle, the optimize() legs pin the
cardinality-adaptive selection thresholds from the Roaring papers, and
the device legs prove run-backed fragments decode to the same
bit-plane slabs as their array/bitmap-backed twins.
"""

import io
import os

import numpy as np
import pytest

from pilosa_tpu.storage import native, roaring
from pilosa_tpu.storage.roaring import (ARRAY_MAX_SIZE, RUN_MAX_SIZE,
                                        Bitmap, Container, Op,
                                        runs_to_values, runs_to_words,
                                        values_to_runs)

KINDS = ("array", "bitmap", "run")


def make_container(kind: str, vals) -> Container:
    """A container of the given kind holding exactly ``vals``."""
    vals = np.asarray(sorted(vals), dtype=np.uint32)
    if kind == "run":
        return Container.from_runs(values_to_runs(vals))
    if kind == "bitmap":
        return Container.from_bitmap(
            runs_to_words(values_to_runs(vals)).copy())
    return Container.from_array(vals)


def runny_set(rng, span=3000, n_points=400, n_runs=3, run_len=200):
    """A value set mixing isolated points and dense intervals."""
    out = set(rng.integers(0, span, size=int(rng.integers(0, n_points)))
              .tolist())
    for _ in range(int(rng.integers(0, n_runs + 1))):
        s = int(rng.integers(0, span))
        out |= set(range(s, min(s + run_len, 1 << 16)))
    return out


class TestRunHelpers:
    def test_values_runs_words_roundtrip_randomized(self):
        rng = np.random.default_rng(1)
        for _ in range(100):
            vals = np.asarray(sorted(runny_set(rng, span=1 << 16)),
                              np.uint32)
            runs = values_to_runs(vals)
            assert np.array_equal(runs_to_values(runs), vals)
            assert np.array_equal(
                roaring.bitmap_words_to_values(runs_to_words(runs)),
                vals)

    def test_run_count_words_matches_array_form(self):
        rng = np.random.default_rng(2)
        for _ in range(60):
            vals = np.asarray(sorted(runny_set(rng, span=1 << 16)),
                              np.uint32)
            if not len(vals):
                continue
            words = runs_to_words(values_to_runs(vals))
            assert (roaring.run_count_words(words)
                    == roaring.run_count_array(vals))

    def test_run_crossing_word_boundaries(self):
        vals = np.arange(60, 70, dtype=np.uint32)  # spans word 0→1
        words = runs_to_words(values_to_runs(vals))
        assert np.array_equal(roaring.bitmap_words_to_values(words),
                              vals)


class TestRunContainerPointOps:
    def test_randomized_add_remove_vs_set_model(self):
        rng = np.random.default_rng(3)
        c = make_container("run", range(100, 400))
        model = set(range(100, 400))
        for _ in range(800):
            v = int(rng.integers(0, 600))
            if rng.random() < 0.5:
                assert c.add(v) == (v not in model)
                model.add(v)
            else:
                assert c.remove(v) == (v in model)
                model.discard(v)
            assert c.n == len(model)
        c.check()
        assert set(c.values().tolist()) == model

    def test_add_merges_adjacent_runs(self):
        c = make_container("run", [1, 2, 4, 5])
        assert c.add(3)
        c.check()
        assert (len(c.runs) - 1) >> 1 == 1

    def test_remove_splits_run(self):
        c = make_container("run", range(10, 20))
        assert c.remove(15)
        c.check()
        assert (len(c.runs) - 1) >> 1 == 2

    def test_contains_rank_count_range(self):
        c = make_container("run", list(range(100, 200)) + [500])
        assert c.contains(150) and not c.contains(200)
        assert c.rank(150) == 51
        assert c.count_range(150, 520) == 51
        assert c.rank(500) == 101

    def test_degrading_run_converts_at_bound(self):
        # Alternating adds fragment the run container; past
        # RUN_MAX_SIZE runs it must convert to a legacy kind.
        c = make_container("run", [0])
        for v in range(2, 2 * (RUN_MAX_SIZE + 10), 2):
            c.add(v)
        c.check()
        assert not c.is_run()
        assert c.n == RUN_MAX_SIZE + 10


class TestAlgebraDifferential:
    """Every op × every operand-kind pair vs the set model."""

    OPS = {
        "intersect": (roaring._intersect, lambda a, b: a & b),
        "union": (roaring._union, lambda a, b: a | b),
        "difference": (roaring._difference, lambda a, b: a - b),
        "xor": (roaring._xor, lambda a, b: a ^ b),
    }

    @pytest.mark.parametrize("ka", KINDS)
    @pytest.mark.parametrize("kb", KINDS)
    def test_container_ops_bit_for_bit(self, ka, kb):
        rng = np.random.default_rng(hash((ka, kb)) % (1 << 32))
        for trial in range(40):
            A = runny_set(rng)
            B = runny_set(rng)
            for name, (fn, model_fn) in self.OPS.items():
                out = fn(make_container(ka, A), make_container(kb, B))
                out.check()
                assert set(out.values().tolist()) == model_fn(A, B), \
                    (name, trial)
            got = roaring._intersection_count(make_container(ka, A),
                                              make_container(kb, B))
            assert got == len(A & B), trial

    def test_empty_and_full_extremes(self):
        full = set(range(1 << 16))
        for ka in KINDS:
            for kb in KINDS:
                for A, B in ((set(), full), (full, set()), (full, full)):
                    a, b = make_container(ka, A), make_container(kb, B)
                    assert (set(roaring._intersect(a, b).values()
                                .tolist()) == (A & B))
                    assert (set(roaring._union(a, b).values()
                                .tolist()) == (A | B))

    def test_bitmap_level_ops_with_mixed_kinds(self):
        """Whole-bitmap algebra over containers of all three kinds in
        one keyspace, vs the set model."""
        rng = np.random.default_rng(9)
        for trial in range(15):
            A, B = set(), set()
            ba, bb = Bitmap(), Bitmap()
            for key in range(4):
                base = key << 16
                sa = runny_set(rng, span=1 << 16)
                sb = runny_set(rng, span=1 << 16)
                A |= {base + v for v in sa}
                B |= {base + v for v in sb}
            ba.add_many(np.array(sorted(A), dtype=np.uint64))
            bb.add_many(np.array(sorted(B), dtype=np.uint64))
            ba.optimize()
            if trial % 2:
                bb.optimize()
            assert set(ba.intersect(bb).values().tolist()) == A & B
            assert set(ba.union(bb).values().tolist()) == A | B
            assert set(ba.difference(bb).values().tolist()) == A - B
            assert set(ba.xor(bb).values().tolist()) == A ^ B
            assert ba.intersection_count(bb) == len(A & B)

    def test_run_op_kinds_feed_counters(self):
        before = roaring.op_counts()
        a = make_container("run", range(100))
        b = make_container("run", range(50, 150))
        roaring._intersect(a, b)
        roaring._union(a, make_container("array", [1, 7]))
        roaring._difference(a, make_container("bitmap", range(0, 60)))
        after = roaring.op_counts()
        assert (after[("intersect", "run_run")]
                == before[("intersect", "run_run")] + 1)
        assert (after[("union", "run_array")]
                == before[("union", "run_array")] + 1)
        assert (after[("difference", "run_bitmap")]
                == before[("difference", "run_bitmap")] + 1)

    def test_galloping_skewed_intersection(self):
        """Lopsided sorted-array operands take the searchsorted
        (galloping) strategy — results identical to the merge path."""
        rng = np.random.default_rng(12)
        big = np.unique(rng.integers(0, 1 << 16, size=20000)
                        ).astype(np.uint32)
        small = np.unique(rng.choice(big, size=8)).astype(np.uint32)
        a, b = Container.from_array(small), Container.from_array(big)
        assert roaring._skewed(small, big)
        out = roaring._intersect(a, b)
        assert np.array_equal(out.values(), small)
        assert roaring._intersection_count(a, b) == len(small)


class TestOptimizeSelection:
    """The cardinality-adaptive thresholds: smallest of 4n / 8192 /
    2+4R wins (arXiv:1603.06549 §3)."""

    def test_one_long_run_wins_over_bitmap(self):
        c = make_container("bitmap", range(10000))
        assert c.optimize() == "run"
        assert c.size_bytes() == 6

    def test_isolated_values_stay_array(self):
        c = make_container("array", range(0, 100, 2))
        assert c.optimize() == "array"

    def test_dense_random_stays_bitmap(self):
        rng = np.random.default_rng(5)
        vals = np.unique(rng.integers(0, 1 << 16, size=30000))
        c = make_container("bitmap", vals)
        assert c.optimize() == "bitmap"

    def test_exact_boundary_prefers_legacy(self):
        # 4 values in 2 runs: run block 2+8=10 > array 16? No: 10 < 16
        # → run. 3 isolated values: run 2+12=14 > array 12 → array.
        assert make_container("array", [1, 2, 10, 11]).optimize() == "run"
        assert make_container("array", [1, 10, 20]).optimize() == "array"

    def test_bitmap_boundary_against_runs(self):
        # n > ARRAY_MAX_SIZE: legacy = 8192 bytes; R = 2047 runs →
        # 2+4*2047 = 8190 < 8192 → run; R = 2048 → 8194 → bitmap.
        vals = []
        for i in range(2047):
            vals.extend((i * 8, i * 8 + 1, i * 8 + 2))
        c = make_container("bitmap", vals)
        assert c.n > ARRAY_MAX_SIZE
        assert c.optimize() == "run"
        vals2 = []
        for i in range(2048):
            vals2.extend((i * 8, i * 8 + 1, i * 8 + 2))
        c2 = make_container("bitmap", vals2)
        assert c2.optimize() == "bitmap"

    def test_bitmap_optimize_reports_kinds(self):
        b = Bitmap()
        b.add_many(np.arange(20000, dtype=np.uint64))          # run
        b.add_many((1 << 16) * 4 + np.arange(0, 20000, 2,
                                             dtype=np.uint64))  # bitmap
        b.add_many((1 << 16) * 8 + np.arange(0, 300, 3,
                                             dtype=np.uint64))  # array
        kinds = b.optimize()
        assert kinds == {"array": 1, "bitmap": 1, "run": 1}
        stats = b.container_stats()
        assert stats["counts"] == {"array": 1, "bitmap": 1, "run": 1}
        assert stats["bytes"]["run"] == 6
        assert stats["intervals"]["run"] == 1


class TestSerializationAndWal:
    def test_snapshot_roundtrip_randomized(self):
        rng = np.random.default_rng(6)
        for trial in range(10):
            b = Bitmap()
            model = set()
            for key in range(int(rng.integers(1, 5))):
                base = key << 16
                s = runny_set(rng, span=1 << 16)
                model |= {base + v for v in s}
            b.add_many(np.array(sorted(model), dtype=np.uint64))
            b.optimize()
            data = b.marshal()
            for mapped in (False, True):
                back = Bitmap.unmarshal(memoryview(data), mapped=mapped)
                back.check()
                assert set(back.values().tolist()) == model
                assert back.marshal() == data

    def test_wal_replay_over_runs_snapshot(self):
        b = Bitmap()
        b.add_many(np.arange(1000, 30000, dtype=np.uint64))
        b.optimize()
        assert b.containers[0].is_run()
        data = b.marshal()
        ops = (Op(roaring.OP_ADD, 30000).marshal()
               + Op(roaring.OP_REMOVE, 1500).marshal()
               + Op(roaring.OP_ADD, 99 << 16).marshal())
        back = Bitmap.unmarshal(memoryview(data + ops))
        model = (set(range(1000, 30001)) | {99 << 16}) - {1500}
        assert set(back.values().tolist()) == model
        assert back.op_n == 3

    def test_torn_tail_after_runs_snapshot(self):
        b = Bitmap()
        b.add_many(np.arange(0, 70000, dtype=np.uint64))
        b.optimize()
        data = b.marshal() + Op(roaring.OP_ADD, 5).marshal()[:7]
        back = Bitmap.unmarshal(memoryview(data),
                                tolerate_torn_tail=True)
        assert back.torn_bytes == 7
        assert back.count() == 70000

    def test_write_frozen_with_runs_falls_back_identically(self,
                                                           tmp_path):
        b = Bitmap()
        b.add_many(np.arange(500, 40000, dtype=np.uint64))
        b.add_many((1 << 20) + np.arange(0, 999, 3, dtype=np.uint64))
        b.optimize()
        frozen = b.freeze()
        assert frozen.has_runs
        buf = io.BytesIO()
        roaring.write_frozen(frozen, buf)
        assert buf.getvalue() == b.marshal()
        p = tmp_path / "snap"
        with open(p, "wb") as f:
            roaring.write_frozen(frozen, f)
        assert p.read_bytes() == b.marshal()

    def test_unmarshal_rejects_truncated_run_block(self):
        b = Bitmap()
        b.add_many(np.arange(100, 50000, dtype=np.uint64))
        b.optimize()
        data = b.marshal()
        with pytest.raises(ValueError, match="out of bounds"):
            Bitmap.unmarshal(memoryview(data[:-3]))


class TestBatchEngineOverRuns:
    """The native batch write engine (and its numpy fallback) must
    transparently upgrade run containers — identical results, WAL
    records only for genuinely changed bits."""

    @pytest.mark.parametrize("force_python", [False, True])
    def test_apply_batch_differential(self, force_python, monkeypatch):
        if force_python:
            monkeypatch.setattr(native, "available", lambda: False)
        rng = np.random.default_rng(8)
        b = Bitmap()
        b.add_many(np.arange(10, 30000, dtype=np.uint64))
        b.add_many((3 << 16) + np.arange(0, 220, 2, dtype=np.uint64))
        b.optimize()
        assert any(c.is_run() for c in b.containers)
        model = set(b.values().tolist())
        wal = io.BytesIO()
        b.op_writer = wal
        adds = np.unique(rng.integers(0, 5 << 16, size=4000)
                         ).astype(np.uint64)
        changed = b.apply_batch(adds, set=True)
        assert set(changed.tolist()) == set(adds.tolist()) - model
        model |= set(adds.tolist())
        rems = np.unique(rng.integers(0, 5 << 16, size=2500)
                         ).astype(np.uint64)
        changed = b.apply_batch(rems, set=False)
        assert set(changed.tolist()) == model & set(rems.tolist())
        model -= set(rems.tolist())
        assert set(b.values().tolist()) == model
        b.check()
        assert not any(c.is_run() for c in b.containers
                       if c.n)  # upgraded by the engine
        # WAL replays to the same state over the pre-batch snapshot.
        pre = Bitmap()
        pre.add_many(np.arange(10, 30000, dtype=np.uint64))
        pre.add_many((3 << 16) + np.arange(0, 220, 2, dtype=np.uint64))
        pre.optimize()
        back = Bitmap.unmarshal(memoryview(pre.marshal()
                                           + wal.getvalue()))
        assert set(back.values().tolist()) == model

    @pytest.mark.parametrize("force_python", [False, True])
    def test_batch_remove_oversized_run_keeps_invariant(
            self, force_python, monkeypatch):
        """A remove against a run container with n > ARRAY_MAX_SIZE
        must come back as a bitmap (or a <=4096 array), never an
        oversized array — the snapshot sizer maps n>4096 to a bitmap
        block, so that state serializes corrupt (review finding)."""
        if force_python:
            monkeypatch.setattr(native, "available", lambda: False)
        b = Bitmap()
        b.add_many(np.arange(0, 10000, dtype=np.uint64))
        b.optimize()
        assert b.containers[0].is_run() and b.containers[0].n == 10000
        changed = b.apply_batch(
            np.arange(0, 20, dtype=np.uint64), set=False)
        assert len(changed) == 20
        c = b.containers[0]
        assert c.n == 9980
        assert c.kind() == "bitmap"
        b.check()
        back = Bitmap.unmarshal(memoryview(b.marshal()))
        assert back.values().tolist() == list(range(20, 10000))
        # Removing below the boundary unpacks to array as usual.
        changed = b.apply_batch(
            np.arange(20, 6000, dtype=np.uint64), set=False)
        assert len(changed) == 5980
        assert b.containers[0].kind() == "array"
        b.check()
        back = Bitmap.unmarshal(memoryview(b.marshal()))
        assert back.values().tolist() == list(range(6000, 10000))

    def test_point_writes_through_bitmap_level(self):
        b = Bitmap()
        b.add_many(np.arange(0, 25000, dtype=np.uint64))
        b.optimize()
        assert b.containers[0].is_run()
        assert not b.add(5)           # already set, run membership
        assert b.remove(100)          # run split via Bitmap._remove
        assert b.add(100)
        assert b.contains(24999)
        assert b.count() == 25000
        assert b.max() == 24999
        assert b.rank(99) == 100


class TestFragmentEndToEnd:
    @pytest.fixture
    def holder(self, tmp_path):
        from pilosa_tpu.models.holder import Holder
        h = Holder(str(tmp_path))
        h.open()
        yield h
        h.close()

    def _run_heavy_frame(self, holder, name="f"):
        from pilosa_tpu import SLICE_WIDTH
        frame = holder.create_index_if_not_exists("r") \
            .create_frame_if_not_exists(name)
        rows, cols = [], []
        for row in range(3):
            # timestamp-view shape: long dense column ranges
            start = row * 10000
            span = np.arange(start, start + 30000, dtype=np.uint64)
            rows.append(np.full(len(span), row, dtype=np.uint64))
            cols.append(span % SLICE_WIDTH)
        frame.import_bits(np.concatenate(rows), np.concatenate(cols))
        return frame

    def test_import_produces_runs_and_snapshot_roundtrips(self, holder):
        frame = self._run_heavy_frame(holder)
        frag = holder.fragment("r", "f", "standard", 0)
        stats = frag.container_stats()
        assert stats["counts"]["run"] > 0, stats
        # WAL-first imports no longer force a synchronous snapshot;
        # take one so the on-disk cookie reflects the run containers.
        frag._join_snapshot()
        frag.snapshot()
        with open(frag.path, "rb") as f:
            assert int.from_bytes(f.read(4),
                                  "little") == roaring.COOKIE_RUNS
        row0 = set(frag.row(0).bits())
        # Point writes (WAL ops) on top of run containers, then reopen.
        frame.set_bit("standard", 0, 12)
        frame.clear_bit("standard", 0, 50)
        holder.close()
        holder.open()
        frag2 = holder.fragment("r", "f", "standard", 0)
        got = set(frag2.row(0).bits())
        assert got == (row0 | {12}) - {50}
        frag2.storage.check()

    def test_run_backed_rows_decode_to_same_device_words(self, holder):
        """pack_row / sparse_row_words over run containers equal the
        legacy-kind decode — the residency upload sees identical
        bit-plane slabs."""
        from pilosa_tpu.ops import packed
        self._run_heavy_frame(holder)
        frag = holder.fragment("r", "f", "standard", 0)
        assert frag.container_stats()["counts"]["run"] > 0
        legacy = Bitmap.unmarshal(memoryview(frag.storage.marshal()))
        for c in legacy.containers:  # force legacy kinds
            if c.runs is not None:
                c._run_to_legacy()
        for row in range(3):
            out_run = np.zeros(packed.WORDS_PER_SLICE, np.uint32)
            packed.pack_storage_row(frag.storage, row, out_run)
            out_legacy = np.zeros(packed.WORDS_PER_SLICE, np.uint32)
            packed.pack_storage_row(legacy, row, out_legacy)
            assert np.array_equal(out_run, out_legacy)
            ir, vr = packed.sparse_row_words(frag.storage, row)
            il, vl = packed.sparse_row_words(legacy, row)
            assert np.array_equal(ir, il) and np.array_equal(vr, vl)

    def test_resident_bytes_shrink_vs_legacy(self, holder):
        self._run_heavy_frame(holder)
        frag = holder.fragment("r", "f", "standard", 0)
        stats = frag.storage.container_stats()
        run_bytes = sum(stats["bytes"].values())
        legacy = Bitmap.unmarshal(memoryview(frag.storage.marshal()))
        for c in legacy.containers:
            if c.runs is not None:
                c._run_to_legacy()
        legacy_bytes = sum(legacy.container_stats()["bytes"].values())
        assert run_bytes < legacy_bytes / 4, (run_bytes, legacy_bytes)

    def test_queries_on_run_backed_fragment_match_legacy_mode(
            self, holder, monkeypatch, tmp_path):
        """The same import with the optimize pass disabled answers
        every query identically (host roaring algebra over runs)."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.storage import fragment as fragment_mod
        self._run_heavy_frame(holder)
        other_dir = tmp_path / "legacy"
        monkeypatch.setattr(fragment_mod, "_RUN_OPTIMIZE", False)
        h2 = Holder(str(other_dir))
        h2.open()
        try:
            self._run_heavy_frame(h2)
            assert (h2.fragment("r", "f", "standard", 0)
                    .container_stats()["counts"]["run"] == 0)
            ex1 = Executor(holder, host="local", use_mesh=False)
            ex2 = Executor(h2, host="local", use_mesh=False)
            queries = [
                'Count(Intersect(Bitmap(rowID=0, frame=f),'
                ' Bitmap(rowID=1, frame=f)))',
                'Count(Union(Bitmap(rowID=0, frame=f),'
                ' Bitmap(rowID=2, frame=f)))',
                'Count(Difference(Bitmap(rowID=1, frame=f),'
                ' Bitmap(rowID=2, frame=f)))',
                'TopN(frame=f, n=2)',
            ]
            for q in queries:
                r1, r2 = ex1.execute("r", q), ex2.execute("r", q)
                if hasattr(r1[0], "bits"):
                    assert list(r1[0].bits()) == list(r2[0].bits()), q
                else:
                    assert r1 == r2, q
        finally:
            h2.close()


class TestObsSurface:
    def test_runtime_collector_publishes_container_mix(self, tmp_path):
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.obs import metrics as obs_metrics
        from pilosa_tpu.obs.runtime import RuntimeCollector
        h = Holder(str(tmp_path))
        h.open()
        try:
            frame = h.create_index_if_not_exists("m") \
                .create_frame_if_not_exists("f")
            cols = np.arange(0, 40000, dtype=np.uint64)
            frame.import_bits(np.zeros(len(cols), np.uint64), cols)
            snap = RuntimeCollector(holder=h).collect()
            mix = snap["holder"]["containers"]
            assert mix["counts"]["run"] >= 1, mix
            assert mix["bytes"]["run"] > 0
            fams = obs_metrics.default_registry().families()
            assert "pilosa_roaring_containers_live" in fams
            assert "pilosa_roaring_container_bytes" in fams
            rendered = obs_metrics.default_registry().render()
            assert 'pilosa_roaring_containers_live{kind="run"}' \
                in rendered
        finally:
            h.close()


class TestCliRunSurface:
    def test_inspect_and_check_report_run_stats(self, tmp_path, capsys):
        from pilosa_tpu.cli.commands import main as cli_main
        b = Bitmap()
        b.add_many(np.arange(100, 30000, dtype=np.uint64))
        b.add_many((2 << 16) + np.arange(0, 100, 2, dtype=np.uint64))
        b.optimize()
        p = tmp_path / "frag"
        p.write_bytes(b.marshal())
        assert cli_main(["check", str(p)]) == 0
        assert ": ok" in capsys.readouterr().out
        assert cli_main(["inspect", str(p)]) == 0
        out = capsys.readouterr().out
        assert "run" in out and "Container Types" in out
        assert "INTERVALS" in out

    def test_check_flags_corrupt_run_invariants(self, tmp_path, capsys):
        from pilosa_tpu.cli.commands import main as cli_main
        b = Bitmap()
        b.add_many(np.arange(100, 30000, dtype=np.uint64))
        b.optimize()
        data = bytearray(b.marshal())
        # Corrupt the run block: overlap the (single) run with a bogus
        # second one by rewriting numRuns and appending garbage is
        # fiddly; instead break the cardinality header (n-1) so the
        # Σ lengths == n invariant trips.
        hdr_off = roaring.HEADER_SIZE + roaring._run_flags_len(1) + 8
        data[hdr_off:hdr_off + 4] = (5).to_bytes(4, "little")
        p = tmp_path / "bad"
        p.write_bytes(bytes(data))
        assert cli_main(["check", str(p)]) == 1
        assert "lengths sum" in capsys.readouterr().out
