"""Tiered-storage tests (ISSUE 16): the working-set manager, block-
granular cold faulting, the blob tier, and the satellites that ride
the PR.

Tier-1 (fast) legs: demote → block-fault → promote round trips proven
bit-for-bit against the all-resident answer (randomized differential),
the ENOSPC-during-demotion and cold-fetch-failure failpoint legs
(degrade per the ``?partial=1``/503 contract — never a wrong answer),
the crash-window reopen rules (stub + data file coexistence, leftover
fetch staging, failpoint-aborted push), eviction honoring per-tenant
cache shares (+ pinned entries), the ``tier.fault`` corrupt leg
(quarantine, not a wrong answer), the whole-leg Sum/Min/Max pushdown
folds, per-tenant dispatch fairness, and the /debug/tier surface. The
real SIGKILL mid-transition soak is additionally ``slow``.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.fault import failpoints
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.storage import bsi
from pilosa_tpu.storage.integrity import CorruptionError
from pilosa_tpu.tier import blob as blob_mod
from pilosa_tpu.tier.ledger import ResidencyLedger
from pilosa_tpu.tier.manager import ColdFetchError, TierManager

pytestmark = pytest.mark.tier


def _holder_with_fragment(path, n_rows=4, seed=7, per_row=3000):
    """A holder with one snapshotted fragment carrying deterministic
    random rows; returns (holder, fragment, {row: sorted bits})."""
    h = Holder(str(path))
    h.open()
    idx = h.create_index("i")
    fr = idx.create_frame("f")
    view = fr.create_view_if_not_exists("standard")
    frag = view.create_fragment_if_not_exists(0)
    rng = np.random.default_rng(seed)
    expect = {}
    for r in range(n_rows):
        cols = np.unique(rng.integers(0, 1 << 20, size=per_row))
        for c in cols.tolist():
            frag.set_bit(r, c)
        expect[r] = sorted(cols.tolist())
    frag.snapshot()
    return h, frag, expect


def _manager(h, tmp, **kw):
    kw.setdefault("resident_budget", 1 << 30)
    kw.setdefault("cold_dir", os.path.join(str(tmp), "_tier"))
    kw.setdefault("blob", "dir")
    mgr = TierManager(h, **kw)
    h.tier = mgr
    mgr.sync()
    return mgr


# -- demotion / block faulting / promotion ------------------------------------


class TestDemoteFault:
    def test_demote_then_block_fault_exact(self, tmp_path):
        h, frag, expect = _holder_with_fragment(tmp_path)
        _manager(h, tmp_path)
        try:
            assert frag.demote_cold() > 0
            assert frag.tier_state == "cold"
            pending0 = len(frag._cold_pending)
            assert pending0 > 0
            # One row's read faults only that row's container blocks.
            assert sorted(frag.row(1).bits()) == expect[1]
            assert 0 < len(frag._cold_pending) < pending0
            # Remaining rows read correctly too (fault as touched).
            for r, bits in expect.items():
                assert sorted(frag.row(r).bits()) == bits
        finally:
            h.close()

    def test_top_promotes_fully(self, tmp_path):
        h, frag, expect = _holder_with_fragment(tmp_path)
        _manager(h, tmp_path)
        try:
            hot_top = [(p.id, p.count) for p in frag.top()]
            assert frag.demote_cold() > 0
            cold_top = [(p.id, p.count) for p in frag.top()]
            assert cold_top == hot_top
            assert frag.tier_state == "hot", \
                "TopN ranks through the count cache — full promote"
        finally:
            h.close()

    def test_randomized_differential_cold_vs_resident(self, tmp_path):
        """The zero-wrong-answers claim: across random demote /
        partial-fault / rechill / promote schedules, every read is
        bit-for-bit the all-resident answer."""
        h, frag, expect = _holder_with_fragment(tmp_path, n_rows=6,
                                                seed=11)
        mgr = _manager(h, tmp_path)
        try:
            hot_counts = {r: frag.row_count(r) for r in expect}
            rng = np.random.default_rng(3)
            for step in range(40):
                op = rng.integers(0, 10)
                if op < 2 and frag.tier_state == "hot":
                    frag.demote_cold()
                elif op < 3 and frag.tier_state == "cold":
                    frag.tier_rechill()
                elif op < 4 and frag.tier_state != "hot":
                    frag.promote(trigger="read")
                r = int(rng.integers(0, len(expect)))
                assert sorted(frag.row(r).bits()) == expect[r], \
                    f"step {step} state {frag.tier_state}"
                assert frag.row_count(r) == hot_counts[r]
            st = mgr.state()
            assert st["enabled"] is True
        finally:
            h.close()

    def test_sync_reconciles_out_of_band_demote(self, tmp_path):
        """An operator-driven demote_cold() bypasses the manager; the
        next sync() must flip the ledger entry to cold (fragment is
        the record) instead of carrying a stale hot footprint, and a
        promote must land the real post-compaction file size."""
        h, frag, _ = _holder_with_fragment(tmp_path)
        mgr = _manager(h, tmp_path)
        try:
            assert mgr.ledger.get(frag).tier == "hot"
            assert frag.demote_cold() > 0
            assert mgr.ledger.get(frag).tier == "hot", \
                "direct demote doesn't notify — sync reconciles"
            mgr.sync()
            e = mgr.ledger.get(frag)
            assert e.tier == "cold"
            assert e.nbytes == os.path.getsize(frag.path)
            frag.promote(trigger="read")
            e = mgr.ledger.get(frag)
            assert e.tier == "hot"
            assert e.nbytes == os.path.getsize(frag.path)
            assert mgr.ledger.resident_bytes() >= e.nbytes
        finally:
            h.close()

    def test_write_on_cold_fragment_promotes_and_lands(self, tmp_path):
        h, frag, expect = _holder_with_fragment(tmp_path)
        _manager(h, tmp_path)
        try:
            assert frag.demote_cold() > 0
            assert frag.set_bit(1, 999_999)
            assert frag.tier_state == "hot"
            assert sorted(frag.row(1).bits()) == sorted(
                expect[1] + [999_999])
        finally:
            h.close()


# -- ENOSPC during demotion ---------------------------------------------------


class TestEnospcDemotion:
    def test_enospc_mid_demotion_keeps_serving(self, tmp_path):
        """A full disk during the demotion snapshot must leave the
        fragment hot, serving, and intact — degradation, never a
        wrong answer."""
        h, frag, expect = _holder_with_fragment(tmp_path)
        mgr = _manager(h, tmp_path)
        try:
            frag.set_bit(0, 777_777)  # op_n > 0 → demotion snapshots
            expect[0] = sorted(expect[0] + [777_777])
            with failpoints.injected("snapshot.write", "enospc"):
                with pytest.raises(OSError):
                    frag.demote_cold()
                assert not mgr._demote(frag, "idle"), \
                    "manager demotion absorbs the OSError"
            assert frag.tier_state == "hot"
            assert mgr.errors >= 1
            for r, bits in expect.items():
                assert sorted(frag.row(r).bits()) == bits
            # Disarmed: demotion lands and the data is still exact.
            assert frag.demote_cold() > 0
            for r, bits in expect.items():
                assert sorted(frag.row(r).bits()) == bits
        finally:
            failpoints.disarm_all()
            h.close()


# -- blob tier: push / fetch / crash windows ----------------------------------


class TestBlobTier:
    def _pushed(self, tmp_path, **holder_kw):
        h, frag, expect = _holder_with_fragment(tmp_path, **holder_kw)
        mgr = _manager(h, tmp_path)
        assert frag.demote_cold() > 0
        assert mgr.push_blob(frag)
        assert frag.tier_state == "blob" and frag.storage is None
        assert os.path.exists(frag.path + ".blob")
        assert not os.path.exists(frag.path)
        return h, frag, expect, mgr

    def test_push_fetch_round_trip_exact(self, tmp_path):
        h, frag, expect, mgr = self._pushed(tmp_path)
        try:
            for r, bits in expect.items():
                assert sorted(frag.row(r).bits()) == bits
            assert frag.tier_state in ("cold", "hot")
            assert not os.path.exists(frag.path + ".blob")
            assert mgr.blob_fetches == 1
        finally:
            h.close()

    def test_stub_survives_reopen(self, tmp_path):
        h, frag, expect, mgr = self._pushed(tmp_path)
        h.close()
        h2 = Holder(str(tmp_path))
        h2.open()
        try:
            frag2 = h2.fragment("i", "f", "standard", 0)
            assert frag2 is not None and frag2.tier_state == "blob"
            _manager(h2, tmp_path)
            for r, bits in expect.items():
                assert sorted(frag2.row(r).bits()) == bits
        finally:
            h2.close()

    def test_crash_window_stub_and_data_file_coexist(self, tmp_path):
        """SIGKILL between stub write and data-file removal leaves
        BOTH on disk: the data file wins on reopen (it was verified
        before the stub landed) and the stub is deleted."""
        h, frag, expect = _holder_with_fragment(tmp_path)
        mgr = _manager(h, tmp_path)
        assert frag.demote_cold() > 0
        keep = frag.path + ".keep"
        shutil.copy(frag.path, keep)
        assert mgr.push_blob(frag)
        os.rename(keep, frag.path)  # restore: the crash window state
        h.close()
        h2 = Holder(str(tmp_path))
        h2.open()
        try:
            frag2 = h2.fragment("i", "f", "standard", 0)
            assert frag2.tier_state == "hot"
            assert not os.path.exists(frag2.path + ".blob"), \
                "data file wins; stale stub removed"
            for r, bits in expect.items():
                assert sorted(frag2.row(r).bits()) == bits
        finally:
            h2.close()

    def test_crash_window_fetch_staging_leftover(self, tmp_path):
        """SIGKILL mid-fetch leaves a ``.fetching`` staging file; the
        retry's os.replace overwrites it and the fetch still lands."""
        h, frag, expect, mgr = self._pushed(tmp_path)
        h.close()
        open(os.path.join(
            os.path.dirname(frag.path),
            os.path.basename(frag.path) + ".fetching"),
            "wb").write(b"torn garbage")
        h2 = Holder(str(tmp_path))
        h2.open()
        try:
            frag2 = h2.fragment("i", "f", "standard", 0)
            assert frag2.tier_state == "blob"
            _manager(h2, tmp_path)
            for r, bits in expect.items():
                assert sorted(frag2.row(r).bits()) == bits
        finally:
            h2.close()

    def test_failed_push_leaves_fragment_cold_and_serving(self,
                                                          tmp_path):
        h, frag, expect = _holder_with_fragment(tmp_path)
        mgr = _manager(h, tmp_path)
        try:
            assert frag.demote_cold() > 0
            with failpoints.injected("tier.fetch", "partition(push)"):
                assert not mgr.push_blob(frag)
            assert frag.tier_state == "cold"
            assert os.path.exists(frag.path)
            for r, bits in expect.items():
                assert sorted(frag.row(r).bits()) == bits
        finally:
            failpoints.disarm_all()
            h.close()

    def test_torn_promotion_degrades_then_heals(self, tmp_path):
        """A fetch torn mid-promotion: the staged .fetching file never
        becomes the data file, the promotion fails blocked (not wrong),
        and the disarmed retry lands the promotion bit-for-bit."""
        h, frag, expect, mgr = self._pushed(tmp_path)
        try:
            with failpoints.injected("tier.fetch", "torn(64)"):
                with pytest.raises(ColdFetchError):
                    frag.promote(trigger="read")
            assert frag.tier_state == "blob"
            assert not os.path.exists(frag.path), \
                "a torn fetch must never become the data file"
            assert mgr.slice_blocked(frag.index, frag.slice)
            failpoints.disarm_all()
            mgr.pass_once()
            assert not mgr.slice_blocked(frag.index, frag.slice)
            frag.promote(trigger="read")
            assert frag.tier_state == "hot"
            for r, bits in expect.items():
                assert sorted(frag.row(r).bits()) == bits
        finally:
            failpoints.disarm_all()
            h.close()

    def test_corrupt_blob_fetch_blocks_never_lies(self, tmp_path):
        """A blob store whose object rotted: the fetch's crc check
        refuses the bytes, the slice is BLOCKED (not served wrong),
        and an intact store unblocks on retry."""
        h, frag, expect, mgr = self._pushed(tmp_path)
        try:
            root = os.path.join(str(tmp_path), "_tier", "blob")
            flipped = []
            for dirpath, _d, files in os.walk(root):
                for name in files:
                    if name.startswith("blk-0-"):
                        p = os.path.join(dirpath, name)
                        raw = bytearray(open(p, "rb").read())
                        raw[0] ^= 0xFF
                        open(p, "wb").write(bytes(raw))
                        flipped.append((p, bytes(raw)))
            assert flipped
            with pytest.raises(ColdFetchError):
                frag.row(0)
            assert mgr.slice_blocked("i", 0)
            assert frag.tier_state == "blob", "no torn local file"
            # Heal the store; the manager's retry pass unblocks.
            for p, raw in flipped:
                fixed = bytearray(raw)
                fixed[0] ^= 0xFF
                open(p, "wb").write(bytes(fixed))
            mgr.pass_once()
            assert not mgr.slice_blocked("i", 0)
            for r, bits in expect.items():
                assert sorted(frag.row(r).bits()) == bits
        finally:
            h.close()


# -- tier.fault corrupt leg ---------------------------------------------------


class TestColdFaultCorruption:
    def test_corrupt_block_quarantines_not_wrong(self, tmp_path):
        h, frag, expect = _holder_with_fragment(tmp_path)
        _manager(h, tmp_path)
        try:
            assert frag.demote_cold() > 0
            with failpoints.injected("tier.fault", "corrupt*1"):
                with pytest.raises(CorruptionError):
                    frag.row(0)
            assert frag.quarantined, \
                "a rotten faulted block is detection → quarantine"
        finally:
            failpoints.disarm_all()
            h.close()


# -- eviction honors per-tenant cache shares ----------------------------------


class _FakeFrag:
    def __init__(self, index, slice):
        self.index, self.frame, self.view = index, "f", "standard"
        self.slice = slice


class TestEvictionShares:
    def test_victims_drain_over_share_tenant_first(self):
        led = ResidencyLedger()
        budget = 1000
        # Tenant b is the OLDEST touch (plain LRU would evict it
        # first); but a is over its share (600 > 0.3×1000) while b is
        # under (200 < 0.5×1000) — so a pays, not the LRU choice.
        fb = _FakeFrag("b", 9)
        led.track(fb, "hot", 200)
        led.touch(fb, "b")
        time.sleep(0.002)
        for i in range(3):
            f = _FakeFrag("a", i)
            led.track(f, "hot", 200)
            led.touch(f, "a")
            time.sleep(0.002)
        shares = {"a": 0.3, "b": 0.5}
        out = led.victims(300, budget, shares)
        assert out and all(k[0] == "a" for k in out), \
            f"over-share tenant pays first, not the LRU pick: {out}"
        # Without shares the same request DOES take b first: the
        # share discipline, not touch order, drove the pick above.
        assert led.victims(300, budget, None)[0][0] == "b"

    def test_under_share_tenant_untouched_until_over_drained(self):
        led = ResidencyLedger()
        fa = _FakeFrag("a", 0)
        led.track(fa, "hot", 800)
        led.touch(fa, "a")
        fb = _FakeFrag("b", 1)
        led.track(fb, "hot", 100)
        led.touch(fb, "b")
        out = led.victims(850, 1000, {"a": 0.2, "b": 0.5})
        assert out[0][0] == "a"
        assert out[1][0] == "b", "only after a is drained"

    def test_pinned_entries_never_victims(self):
        led = ResidencyLedger()
        fa = _FakeFrag("a", 0)
        led.track(fa, "hot", 500)
        led.pin(fa, True)
        fb = _FakeFrag("a", 1)
        led.track(fb, "hot", 500)
        out = led.victims(100, 1000, {"a": 0.1})
        assert out == [("a", "f", "standard", 1)]

    def test_manager_evict_respects_shares_end_to_end(self, tmp_path):
        """Watermark pressure on a real holder: the over-share index
        (= tenant) is demoted, the under-share one stays hot."""
        from pilosa_tpu.sched.tenants import TenantRegistry
        h = Holder(str(tmp_path))
        h.open()
        frags = {}
        for name in ("big", "small"):
            idx = h.create_index(name)
            view = idx.create_frame("f").create_view_if_not_exists(
                "standard")
            frag = view.create_fragment_if_not_exists(0)
            n = 30000 if name == "big" else 200
            for c in range(0, n * 30, 30):
                frag.set_bit(0, c)
            frag.snapshot()
            frags[name] = frag
        size_big = os.path.getsize(frags["big"].path)
        size_small = os.path.getsize(frags["small"].path)
        budget = size_big + size_small  # resident ≈ budget
        reg = TenantRegistry({"big": {"cache_share": 0.1},
                              "small": {"cache_share": 1.0}})
        mgr = TierManager(h, resident_budget=budget,
                          high_watermark=0.8, low_watermark=0.5,
                          cold_dir=os.path.join(str(tmp_path), "_t"),
                          tenants=reg, pace_s=0.0)
        h.tier = mgr
        mgr.sync()
        try:
            for name, frag in frags.items():
                mgr.ledger.touch(frag, name)
            mgr.pass_once()
            assert frags["big"].tier_state == "cold", \
                "over-share tenant absorbs its own pressure"
            assert frags["small"].tier_state == "hot", \
                "under-share tenant's working set survives"
        finally:
            h.close()


# -- serving contract: cold-fetch failure through the server ------------------


def _post(host, path, body=b"", timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=timeout)


def _query(host, index, pql, qs=""):
    return _post(host, f"/index/{index}/query{qs}", pql.encode())


@pytest.fixture
def tiered_solo(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_MESH", "0")
    from pilosa_tpu.server.server import Server
    from pilosa_tpu.utils.config import ScrubConfig, TierConfig
    s = Server(str(tmp_path / "solo"), host="127.0.0.1:0",
               anti_entropy_interval=0, polling_interval=0,
               scrub_config=ScrubConfig(interval=999.0, pace=0.0,
                                        repair=False),
               tier_config=TierConfig(enabled=True,
                                      resident_budget=1 << 30,
                                      idle=999.0, blob_idle=999.0,
                                      interval=999.0, blob="dir",
                                      pace=0.0))
    s.open()
    _post(s.host, "/index/it", b"{}")
    _post(s.host, "/index/it/frame/f", b"{}")
    for col in (3, 9, 77):
        _query(s.host, "it",
               f'SetBit(frame="f", rowID=1, columnID={col})')
    yield s
    failpoints.disarm_all()
    s.close()


class TestColdFetchContract:
    def _to_blob(self, s):
        frag = s.holder.fragment("it", "f", "standard", 0)
        frag.snapshot()
        s.tier.sync()  # hook the fragment (the 999s loop hasn't)
        assert s.tier._demote(frag, "idle")
        assert s.tier.push_blob(frag)
        return frag

    def test_fetch_failure_degrades_then_retry_heals(self,
                                                     tiered_solo):
        s = tiered_solo
        count_q = 'Count(Bitmap(frame="f", rowID=1))'
        assert json.loads(
            _query(s.host, "it", count_q).read())["results"][0] == 3
        self._to_blob(s)
        failpoints.arm("tier.fetch", "partition(fetch)")
        try:
            # Plain query: 5xx, NEVER a wrong count.
            with pytest.raises(urllib.error.HTTPError) as ei:
                _query(s.host, "it", count_q)
            assert ei.value.code in (500, 503)
            # The slice is now blocked: the degraded-read contract.
            assert s.tier.slice_blocked("it", 0)
            resp = _query(s.host, "it", count_q, qs="?partial=1")
            assert resp.status == 200
            assert resp.headers.get("X-Pilosa-Partial") == "0"
            assert json.loads(resp.read())["results"][0] == 0
        finally:
            failpoints.disarm_all()
        # Store reachable again: the manager retry unblocks and the
        # exact answer comes back.
        s.tier.pass_once()
        assert not s.tier.slice_blocked("it", 0)
        assert json.loads(
            _query(s.host, "it", count_q).read())["results"][0] == 3

    def test_debug_tier_surface(self, tiered_solo):
        s = tiered_solo
        out = json.loads(urllib.request.urlopen(
            f"http://{s.host}/debug/tier", timeout=10).read())
        assert out["enabled"] is True
        assert "tiers" in out and "residentBytes" in out
        frag = self._to_blob(s)
        out = json.loads(urllib.request.urlopen(
            f"http://{s.host}/debug/tier?entries=1&pass=1",
            timeout=10).read())
        assert out["tiers"]["blob"]["fragments"] == 1
        assert any(e["tier"] == "blob" for e in out["entries"])
        assert "pass" in out
        # The blackbox carries a tier block.
        bb = s._blackbox_state()
        assert bb["tier"]["enabled"] is True
        assert frag.tier_state == "blob"

    def test_scrub_pass_covers_blob_tier(self, tiered_solo):
        s = tiered_solo
        self._to_blob(s)
        out = s.scrubber.pass_once()
        assert out["fragments"] >= 1 and out["corrupt"] == 0
        # Rot a blob object: the NEXT pass flags it and blocks the
        # slice (no local bytes to quarantine).
        root = os.path.join(s.tier.cold_dir, "blob")
        for dirpath, _d, files in os.walk(root):
            for name in files:
                if name.startswith("blk-"):
                    p = os.path.join(dirpath, name)
                    raw = bytearray(open(p, "rb").read())
                    raw[0] ^= 0xFF
                    open(p, "wb").write(bytes(raw))
        out = s.scrubber.pass_once()
        assert out["corrupt"] == 1


# -- whole-leg Sum/Min/Max pushdown folds -------------------------------------


class TestAggregateLegFolds:
    def _legs(self, rng, n_slices, depth, with_filter):
        """Synthetic per-slice plane rows as roaring bitmaps."""
        from pilosa_tpu.storage import roaring
        legs, values = [], []
        for _s in range(n_slices):
            n = int(rng.integers(1, 50))
            cols = rng.choice(2000, size=n, replace=False)
            vals = rng.integers(0, 1 << depth, size=n)
            rows = {}
            exists = roaring.Bitmap()
            for c, v in zip(cols.tolist(), vals.tolist()):
                exists.add(c)
                for i in range(depth):
                    if (v >> i) & 1:
                        rows.setdefault(i, roaring.Bitmap()).add(c)
            filt = None
            mask = np.ones(n, dtype=bool)
            if with_filter:
                filt = roaring.Bitmap()
                mask = rng.integers(0, 2, size=n).astype(bool)
                for c in cols[mask].tolist():
                    filt.add(c)

            def row(plane, _ex=exists, _rows=rows):
                if plane == bsi.EXISTS_PLANE:
                    return _ex
                return _rows.get(plane, roaring.Bitmap())
            legs.append((row, filt))
            values.extend(vals[mask].tolist())
        return legs, values

    @pytest.mark.parametrize("with_filter", [False, True])
    def test_sum_min_max_many_match_per_slice(self, with_filter):
        rng = np.random.default_rng(5)
        for trial in range(8):
            depth = int(rng.integers(1, 9))
            min_v, max_v = 0, (1 << depth) - 1
            legs, values = self._legs(rng, int(rng.integers(1, 6)),
                                      depth, with_filter)
            got = bsi.sum_count_many(min_v, max_v, legs)
            # Per-slice + combine is the reference semantics.
            ref = None
            for row, filt in legs:
                v = bsi.sum_count(min_v, max_v, row, filter=filt)
                ref = v if ref is None else bsi.combine_sum(ref, v)
            assert (got.value, got.count) == (ref.value, ref.count)
            assert got.value == sum(values)
            for want_min in (True, False):
                got = bsi.min_max_many(min_v, max_v, legs,
                                       want_min=want_min)
                ref = None
                for row, filt in legs:
                    v = bsi.min_max(min_v, max_v, row, filter=filt,
                                    want_min=want_min)
                    ref = (v if ref is None
                           else bsi.combine_min_max(
                               ref, v, want_min=want_min))
                assert (got.value, got.count) == (ref.value,
                                                 ref.count), \
                    f"trial {trial} want_min={want_min}"
                if values:
                    ext = min(values) if want_min else max(values)
                    assert got.value == ext

    def test_executor_aggregate_over_cold_fragments(self, tmp_path):
        """Sum/Min/Max through the executor leg against demoted
        fragments equals the all-resident answer (the pushdown runs
        on faulted-in blocks)."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.frame import Field
        h = Holder(str(tmp_path))
        h.open()
        idx = h.create_index("i")
        fr = idx.create_frame("f")
        fr.create_field(Field("v", 0, 1000))
        rng = np.random.default_rng(13)
        model = {}
        ex = Executor(h, host="local", use_mesh=False)
        try:
            for col in rng.choice(5000, size=300,
                                  replace=False).tolist():
                val = int(rng.integers(0, 1001))
                ex.execute("i", f'SetFieldValue(frame="f",'
                                f' columnID={col}, v={val})')
                model[col] = val
            hot = {}
            for name in ("Sum", "Min", "Max"):
                hot[name] = ex.execute(
                    "i", f'{name}(frame="f", field="v")')[0].to_json()
            assert hot["Sum"]["value"] == sum(model.values())
            assert hot["Min"]["value"] == min(model.values())
            assert hot["Max"]["value"] == max(model.values())
            _manager(h, tmp_path)
            for frag in list(h.iter_fragments()):
                frag.snapshot()
                assert frag.demote_cold() > 0
            for name in ("Sum", "Min", "Max"):
                cold = ex.execute(
                    "i", f'{name}(frame="f", field="v")')[0].to_json()
                assert cold == hot[name], f"{name} differs cold"
        finally:
            ex.close()
            h.close()

    def test_executor_topn_hot_equals_blob(self, tmp_path):
        """Plain TopN through the executor's batched host path ranks
        via the count caches, which demotion drops — a cold/blob
        fragment must promote before ranking, never answer from the
        empty cache (the wrong-answer path the end-to-end drive
        caught)."""
        from pilosa_tpu.executor import Executor
        h, frag, expect = _holder_with_fragment(tmp_path)
        mgr = _manager(h, tmp_path)
        ex = Executor(h, host="local", use_mesh=False)
        try:
            hot = [(p.id, p.count) for p in
                   ex.execute("i", 'TopN(frame="f", n=3)')[0]]
            assert hot, "seed data must rank"
            assert frag.demote_cold() > 0
            assert mgr.push_blob(frag)
            blob = [(p.id, p.count) for p in
                    ex.execute("i", 'TopN(frame="f", n=3)')[0]]
            assert blob == hot, "TopN through blob tier differs"
            assert frag.tier_state == "hot", "TopN fully promotes"
        finally:
            ex.close()
            h.close()


# -- per-tenant device-queue fairness -----------------------------------------


class TestFairDispatch:
    def test_uncontended_fast_path_no_wait(self):
        from pilosa_tpu.parallel.mesh import FairDispatchQueue
        q = FairDispatchQueue(4)
        q.acquire("a")
        q.release()
        st = q.state()
        assert st["waits"] == 0 and st["inFlight"] == 0
        assert st["dispatches"] == 1

    def test_stride_wake_order_is_weighted(self):
        """Deterministic stride order: with slots saturated, waiters
        wake lowest-pass-first — weight 2 tenant b interleaves ahead
        of weight 1 tenant a's backlog."""
        from pilosa_tpu.parallel.mesh import FairDispatchQueue
        weights = {"a": 1.0, "b": 2.0}
        q = FairDispatchQueue(1, weights.get)
        q.acquire("hold")  # saturate the single slot
        order = []
        started = []

        def waiter(tenant):
            started.append(tenant)
            q.acquire(tenant)
            order.append(tenant)
            q.release()

        threads = []
        # Enqueue order: a, a, a, then b, b — strides put b's first
        # two passes (0.5, 1.0) ahead of a's backlog (1.0, 2.0, 3.0).
        for tenant in ("a", "a", "a", "b", "b"):
            t = threading.Thread(target=waiter, args=(tenant,))
            t.start()
            while len(started) < len(threads) + 1:
                time.sleep(0.001)
            deadline = time.monotonic() + 5
            while q.state()["queued"] < len(threads) + 1:
                assert time.monotonic() < deadline
                time.sleep(0.001)
            threads.append(t)
        q.release()  # free the held slot: the queue drains in order
        for t in threads:
            t.join(timeout=5)
        assert order == ["b", "a", "b", "a", "a"]

    def test_server_installs_and_uninstalls(self, tiered_solo):
        from pilosa_tpu.parallel import mesh as mesh_mod
        st = mesh_mod.fair_dispatch_state()
        assert st is not None and st["slots"] >= 1


# -- SIGKILL mid-transition (slow) --------------------------------------------


_KILL_CHILD = r"""
import os, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.tier.manager import TierManager

data = sys.argv[1]
h = Holder(data)
h.open()
idx = h.create_index("i")
view = idx.create_frame("f").create_view_if_not_exists("standard")
frag = view.create_fragment_if_not_exists(0)
rng = np.random.default_rng(17)
for r in range(4):
    for c in np.unique(rng.integers(0, 1 << 20, size=2000)).tolist():
        frag.set_bit(r, c)
frag.snapshot()
mgr = TierManager(h, resident_budget=1 << 30,
                  cold_dir=os.path.join(data, "_tier"), blob="dir",
                  pace_s=0.0)
h.tier = mgr
mgr.sync()
print("READY", flush=True)
while True:  # demote/fault/promote/push/fetch until SIGKILLed
    frag.demote_cold()
    frag.row(1)
    frag.promote(trigger="read")
    frag.demote_cold()
    mgr.push_blob(frag)
    frag.row(2)          # fetch + fault
    frag.promote(trigger="read")
"""


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_mid_transition_reopens_clean(tmp_path):
    """SIGKILL a process hammering demote/promote/push/fetch cycles,
    at random points, repeatedly: every reopen must see EXACTLY the
    snapshotted bits — no tier transition window loses or invents
    data."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child_src = _KILL_CHILD.format(repo=repo)
    script = tmp_path / "child.py"
    script.write_text(child_src)
    data = str(tmp_path / "data")
    rng = np.random.default_rng(17)
    expect = {r: sorted(np.unique(
        rng.integers(0, 1 << 20, size=2000)).tolist())
        for r in range(4)}
    for trial in range(4):
        proc = subprocess.Popen(
            [sys.executable, str(script), data],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        assert proc.stdout.readline().strip() == "READY"
        time.sleep(0.05 + 0.2 * trial)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        h = Holder(data)
        h.open()
        try:
            frag = h.fragment("i", "f", "standard", 0)
            assert frag is not None, f"trial {trial}: fragment gone"
            mgr = TierManager(h, resident_budget=1 << 30,
                              cold_dir=os.path.join(data, "_tier"),
                              blob="dir", pace_s=0.0)
            h.tier = mgr
            mgr.sync()
            for r, bits in expect.items():
                assert sorted(frag.row(r).bits()) == bits, \
                    f"trial {trial} row {r} diverged after SIGKILL"
        finally:
            h.close()
        shutil.rmtree(data, ignore_errors=True)


# -- blob store unit ----------------------------------------------------------


class TestBlobStore:
    def test_open_specs(self, tmp_path):
        assert blob_mod.open_blob_store("", str(tmp_path)) is None
        s = blob_mod.open_blob_store("dir", str(tmp_path))
        assert isinstance(s, blob_mod.LocalDirBlobStore)
        s2 = blob_mod.open_blob_store(
            f"dir:{tmp_path}/custom", str(tmp_path))
        assert "custom" in s2.root
        with pytest.raises(ValueError):
            blob_mod.open_blob_store("s3://nope", str(tmp_path))

    def test_check_deep_walks_blob_stubs(self, tmp_path):
        """``pilosa-tpu check --deep`` covers blob-tier fragments:
        clean verdicts, then a corrupt object flips rc to 1."""
        import argparse
        import io

        from pilosa_tpu.cli import commands as cmds
        h, frag, _ = _holder_with_fragment(tmp_path)
        mgr = _manager(h, tmp_path)
        assert frag.demote_cold() > 0
        assert mgr.push_blob(frag)
        h.close()
        out = io.StringIO()
        rc = cmds.cmd_check(
            argparse.Namespace(paths=[str(tmp_path)], deep=True),
            out, out)
        assert rc == 0 and "blob tier" in out.getvalue()
        root = os.path.join(str(tmp_path), "_tier", "blob")
        for dirpath, _d, files in os.walk(root):
            for name in files:
                if name.startswith("blk-0-"):
                    p = os.path.join(dirpath, name)
                    raw = bytearray(open(p, "rb").read())
                    raw[-1] ^= 0x01
                    open(p, "wb").write(bytes(raw))
        out = io.StringIO()
        rc = cmds.cmd_check(
            argparse.Namespace(paths=[str(tmp_path)], deep=True),
            out, out)
        assert rc == 1 and "CORRUPT" in out.getvalue()
