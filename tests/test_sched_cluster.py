"""Query lifecycle on a REAL 2-node gossip cluster (replicas=1, so
fan-out is mandatory): a peer that stalls mid-fan-out must not hang
the coordinator — the propagated deadline clamps the remote leg's
socket timeout and the coordinator answers 504 within the budget.
Cancellation must release the coordinator's slot and broadcast to the
peer, and neither node may leak registry entries."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402


def _post(host, path, body=b"", timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=timeout).read()


def _get_json(host, path, timeout=10):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


@pytest.fixture
def cluster(tmp_path):
    """Two gossip-joined nodes with bits spanning 4 slices (replicas=1
    → both nodes own some slices, so reads MUST fan out)."""
    pa, pb = free_port(), free_port()
    ga, gb = free_port(), free_port()
    hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    procs, logs = [], []

    def spawn(name, port, internal, seed=""):
        d = tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        env["PILOSA_TPU_WARMUP"] = "0"
        log = open(tmp_path / f"{name}.log", "a")
        logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--cluster.type", "gossip",
                "--cluster.hosts", hosts,
                "--cluster.replicas", "1",
                "--cluster.internal-port", str(internal),
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        procs.append(p)
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    host_a = spawn("a", pa, ga)
    host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
    _post(host_a, "/index/sc", b"{}")
    _post(host_a, "/index/sc/frame/f", b"{}")

    from pilosa_tpu.cluster.client import Client
    import numpy as np
    client = Client(host_a)
    cols = np.arange(0, 4 * SLICE_WIDTH,
                     SLICE_WIDTH // 8).astype(np.uint64)
    client.import_arrays("sc", "f", np.ones(len(cols), np.uint64), cols)

    # Wait until A can answer the full count (slice knowledge of B's
    # slices arrives via broadcast/gossip).
    deadline = time.time() + 30
    while time.time() < deadline:
        got = json.loads(_post(
            host_a, "/index/sc/query",
            b'Count(Bitmap(frame="f", rowID=1))'))["results"][0]
        if got == len(cols):
            break
        time.sleep(0.3)
    assert got == len(cols), got

    yield {"a": host_a, "b": host_b, "procs": procs,
           "n_bits": len(cols)}

    for p in procs:
        try:
            os.kill(p.pid, signal.SIGCONT)  # in case a test left it stopped
        except OSError:
            pass
        try:
            p.send_signal(signal.SIGINT)
        except OSError:
            pass
    for p in procs:
        try:
            p.wait(timeout=20)
        except subprocess.TimeoutExpired:
            p.kill()
    for log in logs:
        log.close()


def test_stalled_peer_returns_deadline_error_within_budget(cluster):
    """SIGSTOP one node mid-cluster: a deadline-carrying query from
    the other must answer 504 in ~the budget (the propagated deadline
    clamps the remote leg's socket timeout; the idempotent retry never
    starts past the budget) instead of hanging for the 30s client
    default × attempts."""
    host_a, procs = cluster["a"], cluster["procs"]
    os.kill(procs[1].pid, signal.SIGSTOP)
    try:
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(host_a, "/index/sc/query?timeout=2s",
                  b'Count(Bitmap(frame="f", rowID=1))', timeout=30)
        elapsed = time.monotonic() - t0
        assert ei.value.code == 504
        assert b"deadline" in ei.value.read().lower()
        # Within budget + scheduling slack, nowhere near a 30s hang.
        assert elapsed < 8, elapsed
        # The coordinator freed everything (bounded grace for the
        # abandoned leg, then the registry must be clean).
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if not _get_json(host_a, "/debug/queries")["queries"]:
                break
            time.sleep(0.2)
        assert _get_json(host_a, "/debug/queries")["queries"] == []
    finally:
        os.kill(procs[1].pid, signal.SIGCONT)
    # A recovered peer serves the same query fine again.
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            got = json.loads(_post(
                host_a, "/index/sc/query?timeout=10s",
                b'Count(Bitmap(frame="f", rowID=1))'))["results"][0]
            if got == cluster["n_bits"]:
                break
        except urllib.error.HTTPError:
            pass
        time.sleep(0.3)
    assert got == cluster["n_bits"]


def test_cancel_releases_coordinator_and_reaches_peer(cluster):
    """DELETE /debug/queries/{id} while the query's remote leg is
    stuck on a stalled peer: the coordinator returns 409 promptly
    (slot + registry freed without waiting out the stalled leg), the
    cancel broadcast reaches the peer, and after the peer resumes
    neither node leaks a registry entry."""
    host_a, host_b, procs = cluster["a"], cluster["b"], cluster["procs"]
    os.kill(procs[1].pid, signal.SIGSTOP)
    res = {}

    def bg():
        t0 = time.monotonic()
        try:
            _post(host_a, "/index/sc/query?timeout=60s",
                  b'Count(Bitmap(frame="f", rowID=1))', timeout=90)
            res["code"] = 200
        except urllib.error.HTTPError as e:
            res["code"] = e.code
        res["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=bg)
    t.start()
    try:
        deadline = time.monotonic() + 10
        qs = []
        while time.monotonic() < deadline and not qs:
            qs = _get_json(host_a, "/debug/queries")["queries"]
            time.sleep(0.05)
        assert qs, "query never became visible on the coordinator"
        q = qs[0]
        # Legs appear once the fan-out dispatches; the query may
        # first spend a bounded moment in the cluster result cache's
        # hit-validation probe (the fixture's convergence loop cached
        # this exact query, and the probe to the STOPPED peer must
        # fail within its ~1s budget before the real fan-out runs).
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not q["legs"]:
            time.sleep(0.05)
            found = [x for x in _get_json(
                host_a, "/debug/queries")["queries"]
                if x["id"] == q["id"]]
            if not found:
                break
            q = found[0]
        assert q["legs"], "no fan-out legs recorded"
        req = urllib.request.Request(
            f"http://{host_a}/debug/queries/{q['id']}", method="DELETE")
        out = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert out["cancelled"] >= 1
        t.join(timeout=15)
        assert res["code"] == 409, res
        # 409 arrived promptly — not held hostage by the stalled leg.
        assert res["elapsed"] < 10, res
        assert _get_json(host_a, "/debug/queries")["queries"] == []
    finally:
        os.kill(procs[1].pid, signal.SIGCONT)
        t.join(timeout=15)
    # After the peer resumes, its leg (which it buffered while
    # stopped) must drain without leaking a registry entry.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if not _get_json(host_b, "/debug/queries")["queries"]:
            break
        time.sleep(0.3)
    assert _get_json(host_b, "/debug/queries")["queries"] == []
