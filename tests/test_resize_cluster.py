"""Elastic resize acceptance (ISSUE 12): a REAL 2→3 node gossip
cluster resize completes under concurrent differential-checked query
AND write load with zero wrong answers, and the SIGKILL chaos legs
(source / target / coordinator killed mid-stream) either complete or
abort back to the old epoch with no data loss.

The fast leg (the 2→3 grow under load) is tier-1; the SIGKILL legs are
``slow`` (multi-process kill/restart) + ``chaos`` + ``resize``."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402

pytestmark = pytest.mark.resize


def _post(host: str, path: str, body: bytes = b"{}") -> bytes:
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=30).read()


def _query(host: str, index: str, body: str):
    return json.loads(_post(host, f"/index/{index}/query",
                            body.encode()))["results"]


def _get(host: str, path: str):
    return json.loads(urllib.request.urlopen(
        f"http://{host}{path}", timeout=10).read())


def _wait_resize(host: str, timeout: float = 120.0) -> dict:
    deadline = time.time() + timeout
    while time.time() < deadline:
        op = _get(host, "/cluster/resize").get("op")
        if op and op["phase"] in ("done", "aborted"):
            return op
        time.sleep(0.2)
    raise AssertionError("resize did not settle in time")


def _metric(host: str, name: str, **labels) -> float:
    with urllib.request.urlopen(f"http://{host}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    want = "".join(sorted(f'{k}="{v}"' for k, v in labels.items()))
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue
        if labels:
            inside = rest[1:rest.index("}")] if rest[0] == "{" else ""
            if "".join(sorted(inside.split(","))) != want:
                continue
        total += float(line.rsplit(" ", 1)[1])
    return total


class _Fleet:
    """Spawn/kill/restart helper for real gossip-cluster children."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.procs: dict[str, subprocess.Popen] = {}
        self.logs: list = []
        self.ports: dict[str, tuple[int, int]] = {}  # name -> (http, gossip)

    def spawn(self, name, cluster_hosts, seed="", cluster=True,
              extra_env=None):
        if name not in self.ports:
            self.ports[name] = (free_port(), free_port())
        port, gport = self.ports[name]
        d = self.tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        env.update(extra_env or {})
        log = open(self.tmp_path / f"{name}.log", "a")
        self.logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--anti-entropy.interval", "300s"]
        if cluster:
            argv += ["--cluster.type", "gossip",
                     "--cluster.hosts", cluster_hosts,
                     "--cluster.replicas", "1",
                     "--cluster.internal-port", str(gport)]
            if seed:
                argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        self.procs[name] = p
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    def host(self, name):
        return f"127.0.0.1:{self.ports[name][0]}"

    def gossip_addr(self, name):
        return f"127.0.0.1:{self.ports[name][1]}"

    def kill(self, name):
        p = self.procs[name]
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)

    def close(self):
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in self.logs:
            log.close()


@pytest.fixture
def fleet(tmp_path):
    f = _Fleet(tmp_path)
    yield f
    f.close()


def _kill_mid_stream(fleet, coord_host, victim, timeout=60.0):
    """SIGKILL ``victim`` once the coordinator has provably streamed
    bytes and is still streaming — the mid-stream crash the chaos
    legs need to land deterministically."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        op = _get(coord_host, "/cluster/resize").get("op") or {}
        if op.get("phase") == "streaming" and op.get("bytesStreamed",
                                                    0) > 0:
            fleet.kill(victim)
            return op
        if op.get("phase") in ("done", "aborted"):
            raise AssertionError(
                f"resize settled ({op.get('phase')}) before the kill"
                f" window — widen the stream pacing")
        time.sleep(0.05)
    raise AssertionError("stream never started")


def _row_counts(host, index, rows):
    return {r: _query(host, index,
                      f'Count(Bitmap(frame="f", rowID={r}))')[0]
            for r in rows}


def _boot_trio(fleet):
    pa, ga = free_port(), free_port()
    pb, gb = free_port(), free_port()
    pc, gc = free_port(), free_port()
    fleet.ports = {"a": (pa, ga), "b": (pb, gb), "c": (pc, gc)}
    hosts2 = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    host_a = fleet.spawn("a", hosts2)
    host_b = fleet.spawn("b", hosts2, seed=f"127.0.0.1:{ga}")
    # The joiner boots with the CURRENT membership (it owns nothing
    # yet) and gossip-joins, which is the documented join procedure
    # (docs/CLUSTER_RESIZE.md).
    host_c = fleet.spawn("c", hosts2, seed=f"127.0.0.1:{ga}")
    return host_a, host_b, host_c


def _import_data(host_a, host_solo, n_slices=4, n_bits=900, seed=23,
                 n_rows=8):
    from pilosa_tpu.cluster.client import Client
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, n_rows, n_bits).astype(np.uint64)
    cols = rng.choice(n_slices * SLICE_WIDTH, size=n_bits,
                      replace=False).astype(np.uint64)
    Client(host_a).import_arrays("rz", "f", rows, cols)
    if host_solo:
        Client(host_solo).import_arrays("rz", "f", rows, cols)
    model: dict = {}
    for r, c in zip(rows.tolist(), cols.tolist()):
        model.setdefault(int(r), set()).add(int(c))
    return model


def _wait_converged(hosts, model, timeout=30.0):
    # Converge on the heaviest row — guaranteed present whatever the
    # row-spread the test chose.
    row = max(model, key=lambda r: len(model[r]))
    want = len(model[row])
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if all(_query(h, "rz",
                          f'Count(Bitmap(frame="f", rowID={row}))')[0]
                   == want for h in hosts):
                return
        except Exception:  # noqa: BLE001 - still converging
            pass
        time.sleep(0.3)
    raise AssertionError("cluster did not converge on seeded data")


def test_real_2_to_3_resize_under_load(fleet, tmp_path):
    """THE acceptance leg: a live gossip cluster grows 2→3 under
    concurrent write + differential-checked query load; every answer
    during the migration is bit-for-bit the single-node reference's,
    and afterwards all three nodes (and the moved slices' new owner)
    agree with it exactly."""
    host_a, host_b, host_c = _boot_trio(fleet)
    host_s = fleet.spawn("solo", "", cluster=False)
    for h in (host_a, host_s):
        _post(h, "/index/rz", b"{}")
        _post(h, "/index/rz/frame/f", b"{}")
    model = _import_data(host_a, host_s)
    _wait_converged([host_a, host_b], model)

    stop = threading.Event()
    errors: list = []
    writes_done: list = []

    def loadgen():
        """Writes to row 50 (mirrored to the reference under a lock-
        step: cluster first, then solo, count recorded only after
        both acked) + stable-row differentials from both old
        coordinators."""
        i = 0
        while not stop.is_set():
            col = int(4 * SLICE_WIDTH - 1 - i)
            i += 1
            try:
                _query((host_a, host_b)[i % 2], "rz",
                       f'SetBit(frame="f", rowID=50, columnID={col})')
                _query(host_s, "rz",
                       f'SetBit(frame="f", rowID=50, columnID={col})')
                writes_done.append(col)
                for h in (host_a, host_b):
                    got = _query(
                        h, "rz",
                        'Count(Bitmap(frame="f", rowID=4))')[0]
                    if got != len(model[4]):
                        errors.append(("stable-row", h, got,
                                       len(model[4])))
            except Exception as e:  # noqa: BLE001 - recorded
                errors.append(("load", repr(e)))
            time.sleep(0.01)

    t = threading.Thread(target=loadgen)
    t.start()
    try:
        _post(host_a, "/cluster/resize", json.dumps(
            {"hosts": [host_a, host_b, host_c]}).encode())
        op = _wait_resize(host_a)
    finally:
        stop.set()
        t.join()
    assert op["phase"] == "done", op
    assert not errors, errors[:5]
    assert writes_done, "load generator made no progress"

    # Every node is on epoch 1 with three members.
    for h in (host_a, host_b, host_c):
        topo = _get(h, "/debug/topology")
        assert topo["epoch"] == 1, (h, topo["epoch"])
        assert sorted(topo["nodes"]) == sorted(
            [host_a, host_b, host_c])
        assert topo["resize"] is None

    # Full differential vs the reference, from every coordinator.
    want = _row_counts(host_s, "rz", list(range(8)) + [50])
    for h in (host_a, host_b, host_c):
        assert _row_counts(h, "rz", list(range(8)) + [50]) == want, h

    # The migration genuinely moved data and the metrics saw it.
    assert op["slicesMoved"] >= 1 and op["bytesStreamed"] > 0
    assert _metric(host_a, "pilosa_resize_slices_moved_total") >= 1
    assert _metric(host_a, "pilosa_resize_stream_bytes_total") > 0

    # The new owner serves its moved slices: C's topology shows it
    # owning at least one slice.
    topo_c = _get(host_c, "/debug/topology")
    owners = topo_c["indexes"]["rz"]["owners"]
    assert any(host_c in v for v in owners.values()), owners


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_target_mid_stream_aborts_cleanly(fleet):
    """SIGKILL the stream TARGET mid-migration: the coordinator
    aborts back to the old epoch; the surviving 2-node cluster
    answers exactly (no data loss — old owners never dropped
    anything)."""
    host_a, host_b, host_c = _boot_trio(fleet)
    for h in (host_a,):
        _post(h, "/index/rz", b"{}")
        _post(h, "/index/rz/frame/f", b"{}")
    # Rows spread over many 100-row checksum blocks so the paced
    # stream stays in flight long enough to kill mid-stream.
    model = _import_data(host_a, None, n_bits=2400, n_rows=700)
    _wait_converged([host_a, host_b], model)
    _post(host_a, "/debug/failpoints", json.dumps(
        {"site": "resize.stream", "spec": "delay(300ms)"}).encode())
    _post(host_a, "/cluster/resize", json.dumps(
        {"hosts": [host_a, host_b, host_c]}).encode())
    _kill_mid_stream(fleet, host_a, "c")
    op = _wait_resize(host_a, timeout=180.0)
    _post(host_a, "/debug/failpoints", json.dumps(
        {"site": "resize.stream", "spec": "off"}).encode())
    assert op["phase"] == "aborted", op
    for h in (host_a, host_b):
        topo = _get(h, "/debug/topology")
        assert topo["epoch"] == 0 and topo["resize"] is None, (h, topo)
    want = {r: len(model.get(r, set())) for r in range(8)}
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            if (_row_counts(host_a, "rz", range(8)) == want
                    and _row_counts(host_b, "rz", range(8)) == want):
                break
        except Exception:  # noqa: BLE001 - breakers settling
            pass
        time.sleep(0.5)
    assert _row_counts(host_a, "rz", range(8)) == want
    assert _row_counts(host_b, "rz", range(8)) == want


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_source_mid_stream_aborts_cleanly(fleet):
    """SIGKILL a SOURCE owner mid-stream: the coordinator cannot
    finish the diff and aborts; after the source restarts, the old
    epoch answers exactly and a retry completes."""
    host_a, host_b, host_c = _boot_trio(fleet)
    _post(host_a, "/index/rz", b"{}")
    _post(host_a, "/index/rz/frame/f", b"{}")
    model = _import_data(host_a, None, n_bits=2400, n_rows=700)
    _wait_converged([host_a, host_b], model)
    _post(host_a, "/debug/failpoints", json.dumps(
        {"site": "resize.stream", "spec": "delay(300ms)"}).encode())
    _post(host_a, "/cluster/resize", json.dumps(
        {"hosts": [host_a, host_b, host_c]}).encode())
    _kill_mid_stream(fleet, host_a, "b")  # a source owner
    op = _wait_resize(host_a, timeout=180.0)
    _post(host_a, "/debug/failpoints", json.dumps(
        {"site": "resize.stream", "spec": "off"}).encode())
    assert op["phase"] == "aborted", op
    assert _get(host_a, "/debug/topology")["epoch"] == 0
    # Restart the killed source from its data dir; retry completes.
    hosts2 = f"{host_a},{fleet.host('b')}"
    fleet.spawn("b", hosts2,
                seed=f"{fleet.gossip_addr('a')}")
    _wait_converged([host_a, fleet.host("b")], model)
    _post(host_a, "/cluster/resize", json.dumps(
        {"hosts": [host_a, host_b, host_c]}).encode())
    op = _wait_resize(host_a, timeout=180.0)
    assert op["phase"] == "done", op
    want = {r: len(model.get(r, set())) for r in range(8)}
    for h in (host_a, host_b, host_c):
        assert _row_counts(h, "rz", range(8)) == want, h


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_coordinator_journal_recovery(fleet):
    """SIGKILL the COORDINATOR mid-stream: the peers hold the
    installed state until the coordinator restarts, replays its
    journal, and (pre-flip) aborts the resize back to the old epoch
    cluster-wide — then a clean retry completes."""
    host_a, host_b, host_c = _boot_trio(fleet)
    _post(host_a, "/index/rz", b"{}")
    _post(host_a, "/index/rz/frame/f", b"{}")
    model = _import_data(host_a, None, n_bits=2400, n_rows=700)
    _wait_converged([host_a, host_b], model)
    _post(host_a, "/debug/failpoints", json.dumps(
        {"site": "resize.stream", "spec": "delay(300ms)"}).encode())
    _post(host_a, "/cluster/resize", json.dumps(
        {"hosts": [host_a, host_b, host_c]}).encode())
    _kill_mid_stream(fleet, host_a, "a")
    # B still carries the installed (migrating) state.
    assert _get(host_b, "/debug/topology")["resize"] is not None
    # Restart the coordinator on its data dir: journal recovery
    # aborts and broadcasts the abort.
    hosts2 = f"{fleet.host('a')},{host_b}"
    fleet.spawn("a", hosts2, seed=f"{fleet.gossip_addr('b')}")
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            ta = _get(host_a, "/debug/topology")
            tb = _get(host_b, "/debug/topology")
            tc = _get(host_c, "/debug/topology")
            if (ta["resize"] is None and tb["resize"] is None
                    and tc["resize"] is None and ta["epoch"] == 0):
                break
        except Exception:  # noqa: BLE001 - restarting
            pass
        time.sleep(0.5)
    assert _get(host_b, "/debug/topology")["resize"] is None
    _wait_converged([host_a, host_b], model)
    want = {r: len(model.get(r, set())) for r in range(8)}
    assert _row_counts(host_a, "rz", range(8)) == want
    assert _row_counts(host_b, "rz", range(8)) == want
    # Clean retry from the restarted coordinator completes.
    _post(host_a, "/cluster/resize", json.dumps(
        {"hosts": [host_a, host_b, host_c]}).encode())
    op = _wait_resize(host_a, timeout=180.0)
    assert op["phase"] == "done", op
    for h in (host_a, host_b, host_c):
        assert _row_counts(h, "rz", range(8)) == want, h
