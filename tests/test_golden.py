"""Golden interchange fixtures: byte-level compatibility with the
reference file format.

The fixture bytes under tests/golden/ are hand-assembled from the
documented reference layout (snapshot: roaring.go:475-614; op records:
roaring.go:1560-1626) by make_golden.py, independent of our serializer.
Both directions are proven: load golden → exact bit sets and canonical
re-serialization; build via our API → bytes identical to golden.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from pilosa_tpu.storage.roaring import Bitmap

HERE = os.path.dirname(os.path.abspath(__file__))
GOLDEN = os.path.join(HERE, "golden")

sys.path.insert(0, GOLDEN)
import make_golden  # noqa: E402

SIMPLE = [1, 5, 100, 65535]
MULTI = (list(range(10))
         + [65536 + v for v in make_golden.BITMAP_LOWS]
         + [(make_golden.HIGH_KEY << 16) + 123])
REPLAYED = sorted({1, 5, 65535, 42, 2 * 65536 + 7})
RUNS = list(make_golden.RUN_VALUES)
RUNS_MIXED = (list(make_golden.ARRAY_VALUES)
              + [65536 + v for v in make_golden.RUN_VALUES]
              + [2 * 65536 + v for v in make_golden.BITMAP_LOWS]
              + [(make_golden.HIGH_KEY << 16) + v
                 for v in (7, 8, 9, 10, 500)])
RUNS_REPLAYED = sorted((set(make_golden.RUN_VALUES)
                        | {5000, 3 * 65536 + 9}) - {2000, 65535})


def load(name: str) -> bytes:
    with open(os.path.join(GOLDEN, name), "rb") as f:
        return f.read()


def test_fixtures_match_generator():
    """The committed binaries must be byte-identical to what the
    documented-layout generator emits — fixtures cannot rot, and a
    generator edit that diverges from the committed bytes fails here."""
    for name, data in make_golden.fixtures().items():
        assert load(name) == data, name


def test_generator_cli_writes_to_dir(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(GOLDEN, "make_golden.py"),
         str(tmp_path)], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert (tmp_path / "simple_array.roaring").read_bytes() == \
        load("simple_array.roaring")


@pytest.mark.parametrize("name,expected", [
    ("empty.roaring", []),
    ("simple_array.roaring", SIMPLE),
    ("multi_container.roaring", MULTI),
    ("with_oplog.roaring", REPLAYED),
    ("runs.roaring", RUNS),
    ("runs_mixed.roaring", RUNS_MIXED),
    ("runs_oplog.roaring", RUNS_REPLAYED),
])
def test_load_golden(name, expected):
    bm = Bitmap.unmarshal(memoryview(load(name)))
    assert bm.values().tolist() == expected
    assert bm.count() == len(expected)


def test_load_golden_checks_op_checksum():
    data = bytearray(load("with_oplog.roaring"))
    data[-6] ^= 0xFF  # corrupt an op value byte → checksum mismatch
    with pytest.raises(Exception, match="(?i)checksum"):
        Bitmap.unmarshal(memoryview(bytes(data)))


def test_emit_matches_golden():
    """Bitmaps built through OUR API serialize to the exact golden
    bytes (including the bitmap-kind container and the 48-bit key)."""
    for name, values in (("empty.roaring", []),
                         ("simple_array.roaring", SIMPLE),
                         ("multi_container.roaring", MULTI)):
        bm = Bitmap()
        for v in values:
            bm.add(v)
        assert bm.marshal() == load(name), name


def test_replay_reserialize_matches_expected():
    """load(snapshot+ops) → write_to == the canonical snapshot of the
    post-replay state (golden, generator-built)."""
    bm = Bitmap.unmarshal(memoryview(load("with_oplog.roaring")))
    assert bm.marshal() == load("with_oplog.expected.roaring")


def test_mutate_appends_reference_ops(tmp_path):
    """Ops appended through our op_writer parse as reference op records
    (typ/value/FNV-1a) and replay identically."""
    path = tmp_path / "frag"
    path.write_bytes(load("simple_array.roaring"))
    with open(path, "ab") as w:
        bm = Bitmap.unmarshal(memoryview(load("simple_array.roaring")))
        bm.op_writer = w
        bm.add(777)
        bm.remove(5)
    raw = path.read_bytes()
    ops = raw[len(load("simple_array.roaring")):]
    assert len(ops) == 2 * 13
    # Validate against the generator's documented-layout op encoder.
    assert ops == make_golden.op(0, 777) + make_golden.op(1, 5)
    replayed = Bitmap.unmarshal(memoryview(raw))
    assert replayed.values().tolist() == sorted({1, 100, 65535, 777})


class TestRunsGolden:
    """Byte-level interchange for the 12347 runs format, both
    directions, against the independent hand-assembled layout."""

    def test_load_keeps_run_kind(self):
        bm = Bitmap.unmarshal(memoryview(load("runs.roaring")))
        assert bm.containers[0].is_run()
        bm.check()

    def test_emit_matches_golden(self):
        """optimize() + marshal on a bitmap built through our API
        emits the exact hand-assembled runs bytes (cookie, flag
        bitset, cardinality headers, interval blocks)."""
        bm = Bitmap()
        bm.add_many(np.array(RUNS, dtype=np.uint64))
        bm.optimize()
        assert bm.containers[0].is_run()
        assert bm.marshal() == load("runs.roaring")

    def test_mixed_emit_matches_golden(self):
        bm = Bitmap()
        bm.add_many(np.array(RUNS_MIXED, dtype=np.uint64))
        bm.optimize()
        kinds = [c.kind() for c in bm.containers]
        assert kinds == ["array", "run", "bitmap", "run"], kinds
        assert bm.marshal() == load("runs_mixed.roaring")

    def test_replay_mutates_runs_and_reserializes(self):
        """Op-log replay against run containers (edge extension, run
        split, run deletion) then canonical re-serialization."""
        bm = Bitmap.unmarshal(memoryview(load("runs_oplog.roaring")))
        assert bm.values().tolist() == RUNS_REPLAYED
        ref = Bitmap()
        ref.add_many(np.array(RUNS_REPLAYED, dtype=np.uint64))
        ref.optimize()
        got = Bitmap.unmarshal(memoryview(bm.marshal()))
        got.check()
        assert got.values().tolist() == RUNS_REPLAYED

    def test_mapped_load_is_zero_copy_and_reserializes(self):
        data = load("runs_mixed.roaring")
        bm = Bitmap.unmarshal(memoryview(data), mapped=True)
        run_conts = [c for c in bm.containers if c.is_run()]
        assert run_conts and all(c.mapped for c in run_conts)
        assert bm.marshal() == data

    def test_no_runs_never_uses_runs_cookie(self):
        """A snapshot whose optimize() picked no run containers must
        stay byte-compatible with the legacy 12346 vintage."""
        bm = Bitmap()
        for v in (1, 5, 70000):
            bm.add(v)
        bm.optimize()
        assert bm.marshal()[:4] == load("empty.roaring")[:4]


def test_array_values_roundtrip_u32_width():
    """Array containers are u32-per-value on disk (roaring.go:577) —
    reload across the array/bitmap conversion boundary stays exact."""
    bm = Bitmap()
    vals = list(range(0, 4097 * 3, 3))  # crosses ARRAY_MAX → bitmap kind
    for v in vals:
        bm.add(v)
    bm2 = Bitmap.unmarshal(memoryview(bm.marshal()))
    assert np.array_equal(bm2.values(), np.array(vals, dtype=np.uint64))
