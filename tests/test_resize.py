"""Elastic cluster resize (ISSUE 12): movement-set math, epoch
lifecycle, the journal, the streamer, double-reads, and the in-process
coordinator protocol with failpoint chaos.

The real multi-process gossip legs (SIGKILL of source / target /
coordinator, partition during the flip) live in
tests/test_resize_cluster.py; everything here runs in-process and
tier-1."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))

from pilosa_tpu import SLICE_WIDTH  # noqa: E402
from pilosa_tpu.cluster import resize as resize_mod  # noqa: E402
from pilosa_tpu.cluster.broadcast import (  # noqa: E402
    ResizeMessage, marshal_message, unmarshal_message)
from pilosa_tpu.cluster.topology import (  # noqa: E402
    RESIZE_DRAINING, RESIZE_MIGRATING, Cluster, Node, jump_hash,
    movement, new_cluster, owner_hosts)
from pilosa_tpu.errors import PilosaError  # noqa: E402
from pilosa_tpu.executor import ExecOptions, Executor  # noqa: E402
from pilosa_tpu.fault import failpoints  # noqa: E402
from pilosa_tpu.models.holder import Holder  # noqa: E402
from pilosa_tpu.obs import metrics as obs_metrics  # noqa: E402
from pilosa_tpu.pql.parser import parse as parse_pql  # noqa: E402

pytestmark = pytest.mark.resize


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


# ---------------------------------------------------------------------------
# movement-set math: jump-hash minimality (ISSUE 12 satellite)


class TestMovementMinimality:
    PARTITION_N = 256  # higher resolution than the runtime default

    def _hosts(self, n):
        return [f"node{i}:1" for i in range(n)]

    @pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13])
    def test_grow_moves_one_over_n_plus_one(self, n):
        """Appending one host relocates ~1/(n+1) of partitions —
        the jump-hash minimality the whole migration cost story rests
        on (Lamping & Veach)."""
        old = self._hosts(n)
        new = old + [f"node{n}:1"]
        mv = movement(old, new, self.PARTITION_N, 1)
        frac = len(mv) / self.PARTITION_N
        want = 1.0 / (n + 1)
        # Generous tolerance: 256 partitions is a small sample.
        assert abs(frac - want) < max(0.08, 2.5 * want), (
            f"n={n}: moved {frac:.3f}, expected ~{want:.3f}")

    @pytest.mark.parametrize("n", [2, 3, 5, 8])
    def test_grow_never_moves_between_survivors(self, n):
        """Every relocated partition's new owner set includes the ADDED
        host — growing never shuffles a partition between two
        surviving old owners (replica_n=1: the destination IS the new
        host)."""
        old = self._hosts(n)
        added = f"node{n}:1"
        mv = movement(old, old + [added], self.PARTITION_N, 1)
        assert mv, "growing a cluster must move something"
        for p, (o, nw) in mv.items():
            assert nw == (added,), (
                f"partition {p} moved {o} -> {nw}: relocation between"
                f" surviving owners")

    def test_grow_with_replicas_primary_stays_or_is_added(self):
        """With replica_n=2 the replica RING can shift a successor,
        but the PRIMARY of a moved partition either stays put or
        becomes the added host — jump hash never reassigns a primary
        between surviving buckets."""
        old = self._hosts(4)
        added = "node4:1"
        mv = movement(old, old + [added], self.PARTITION_N, 2)
        assert mv
        for p, (o, nw) in mv.items():
            assert nw[0] in (o[0], added), (
                f"partition {p}: primary {o[0]} -> {nw[0]} between"
                f" survivors")

    def test_shrink_of_last_host_mirrors_grow(self):
        """Removing the most-recently-added host is the exact inverse
        of adding it: the same partitions move, back to their old
        owners."""
        old = self._hosts(5)
        grown = old + ["node5:1"]
        mv_grow = movement(old, grown, self.PARTITION_N, 1)
        mv_shrink = movement(grown, old, self.PARTITION_N, 1)
        assert set(mv_grow) == set(mv_shrink)
        for p in mv_grow:
            assert mv_grow[p] == (mv_shrink[p][1], mv_shrink[p][0])

    def test_slice_level_movement_matches_partition_movement(self):
        """Per-slice relocation fraction over a real Cluster follows
        the per-partition movement (slices hash uniformly into
        partitions)."""
        old = self._hosts(4)
        cl = new_cluster(old)
        mv = movement(old, old + ["node4:1"], cl.partition_n, 1)
        moved = sum(1 for s in range(512)
                    if cl.partition("i", s) in mv)
        # 16 partitions: the moved fraction is len(mv)/16 exactly in
        # expectation.
        assert abs(moved / 512 - len(mv) / cl.partition_n) < 0.1

    def test_owner_hosts_matches_cluster_partition_nodes(self):
        hosts = self._hosts(5)
        cl = new_cluster(hosts, replica_n=2)
        for p in range(cl.partition_n):
            assert owner_hosts(hosts, p, 2, jump_hash) == tuple(
                n.host for n in cl.partition_nodes(p))


# ---------------------------------------------------------------------------
# topology: epoch lifecycle + union placement + read fencing


class TestTopologyResizeLifecycle:
    def _cluster(self):
        return new_cluster(["a:1", "b:1"])

    def _moving_slice(self, cl, rs, index="i"):
        for s in range(64):
            mv = cl.moving_slice(index, s)
            if mv is not None:
                return s, mv
        pytest.skip("no moving slice in range")

    def test_install_flip_finalize(self):
        cl = self._cluster()
        rs = cl.install_resize("r1", ["a:1", "b:1", "c:1"])
        assert cl.epoch == 0 and rs.phase == RESIZE_MIGRATING
        s, (phase, old, new) = self._moving_slice(cl, rs)
        assert phase == RESIZE_MIGRATING
        assert "c:1" in new and "c:1" not in old
        # Union write placement includes the target; reads stay old.
        write_hosts = [n.host for n in cl.fragment_nodes("i", s)]
        read_hosts = [n.host for n in cl.read_nodes("i", s)]
        assert "c:1" in write_hosts
        assert "c:1" not in read_hosts
        assert cl.owns_fragment("c:1", "i", s)       # write-accept
        assert not cl.read_allowed("c:1", "i", s)    # read-fenced
        # Flip: atomic switch, draining keeps the union.
        assert cl.flip_epoch("r1") is True
        assert cl.flip_epoch("r1") is False  # idempotent
        assert cl.epoch == 1 and len(cl.nodes) == 3
        assert cl.resize.phase == RESIZE_DRAINING
        read_hosts = [n.host for n in cl.read_nodes("i", s)]
        write_hosts = [n.host for n in cl.fragment_nodes("i", s)]
        assert "c:1" in read_hosts          # new owner serves
        assert set(old) <= set(write_hosts)  # union writes continue
        # Old owner still read-valid while draining (both complete).
        assert any(h in read_hosts for h in old)
        # Finalize: union drops; old owner keeps WRITE-accepting
        # within grace, never read authority.
        assert cl.finalize_resize("r1", grace_s=60.0)
        assert cl.resize is None
        owners_now = [n.host for n in cl.fragment_nodes("i", s)]
        assert "c:1" in owners_now
        for h in old:
            if h not in owners_now:
                assert cl.owns_fragment(h, "i", s)      # grace
                assert not cl.read_allowed(h, "i", s)   # fenced

    def test_second_resize_id_refused(self):
        cl = self._cluster()
        cl.install_resize("r1", ["a:1", "b:1", "c:1"])
        cl.install_resize("r1", ["a:1", "b:1", "c:1"])  # idempotent
        with pytest.raises(ValueError):
            cl.install_resize("r2", ["a:1"])

    def test_abort_pre_flip_and_post_flip(self):
        cl = self._cluster()
        cl.install_resize("r1", ["a:1", "b:1", "c:1"])
        assert cl.abort_resize("r1")
        assert cl.resize is None and cl.epoch == 0
        assert not cl.abort_resize("r1")  # idempotent
        # Post-flip abort reverts nodes AND epoch.
        cl.install_resize("r2", ["a:1", "b:1", "c:1"])
        cl.flip_epoch("r2")
        assert cl.epoch == 1 and len(cl.nodes) == 3
        assert cl.abort_resize("r2")
        assert cl.epoch == 0 and len(cl.nodes) == 2
        assert [n.host for n in cl.nodes] == ["a:1", "b:1"]

    def test_grace_expires(self):
        cl = self._cluster()
        cl.install_resize("r1", ["a:1", "b:1", "c:1"])
        s, (_, old, _new) = self._moving_slice(cl, cl.resize)
        cl.flip_epoch("r1")
        cl.finalize_resize("r1", grace_s=0.0)
        time.sleep(0.01)
        owners_now = {n.host for n in cl.fragment_nodes("i", s)}
        for h in old:
            if h not in owners_now:
                assert not cl.owns_fragment(h, "i", s)

    def test_non_moving_slices_identical_across_epochs(self):
        """The mixed-epoch-unobservable argument: every slice NOT in
        the movement set has the same owner set before and after the
        flip."""
        cl = self._cluster()
        before = {s: tuple(n.host for n in cl.fragment_nodes("i", s))
                  for s in range(64)}
        cl.install_resize("r1", ["a:1", "b:1", "c:1"])
        moving = {s for s in range(64)
                  if cl.moving_slice("i", s) is not None}
        cl.flip_epoch("r1")
        cl.finalize_resize("r1", grace_s=0.0)
        after = {s: tuple(n.host for n in cl.fragment_nodes("i", s))
                 for s in range(64)}
        for s in range(64):
            if s not in moving:
                assert before[s] == after[s], f"slice {s} moved"
            else:
                assert set(before[s]) != set(after[s])


# ---------------------------------------------------------------------------
# ResizeMessage wire + journal


class TestWireAndJournal:
    def test_resize_message_round_trip(self):
        m = ResizeMessage(id="abc", phase="flip", epoch=3,
                          old_hosts=["a:1"], new_hosts=["a:1", "b:1"],
                          coordinator="a:1")
        got = unmarshal_message(marshal_message(m))
        assert isinstance(got, ResizeMessage)
        assert (got.id, got.phase, got.epoch) == ("abc", "flip", 3)
        assert got.old_hosts == ["a:1"]
        assert got.new_hosts == ["a:1", "b:1"]
        assert got.coordinator == "a:1"

    def test_journal_atomic_and_in_flight(self, tmp_path):
        j = resize_mod.ResizeJournal.for_data_dir(str(tmp_path))
        assert j.load() is None
        j.write(id="r1", phase=resize_mod.PHASE_STREAMING,
                old=["a:1"], new=["a:1", "b:1"], epochFrom=0)
        j2 = resize_mod.ResizeJournal.for_data_dir(str(tmp_path))
        state = j2.load()
        assert state["id"] == "r1" and j2.in_flight()
        j2.write(phase=resize_mod.PHASE_DONE)
        j3 = resize_mod.ResizeJournal.for_data_dir(str(tmp_path))
        j3.load()
        assert not j3.in_flight()

    def test_journal_aborted_needs_ack(self, tmp_path):
        j = resize_mod.ResizeJournal.for_data_dir(str(tmp_path))
        j.write(id="r1", phase=resize_mod.PHASE_ABORTED,
                abortAcked=False)
        assert j.in_flight()  # peers may still hold installed state
        j.write(abortAcked=True)
        assert not j.in_flight()

    def test_torn_journal_ignored(self, tmp_path):
        path = os.path.join(str(tmp_path), resize_mod.JOURNAL_FILE)
        with open(path, "w") as f:
            f.write('{"version": 1, "phase": "stre')  # torn write
        j = resize_mod.ResizeJournal(path)
        assert j.load() is None and not j.in_flight()


# ---------------------------------------------------------------------------
# executor: read fencing, double reads, cache invalidation


def must_set(holder, index, frame, row, col, view="standard"):
    idx = holder.create_index_if_not_exists(index)
    f = idx.create_frame_if_not_exists(frame)
    f.set_bit(view, row, col)


class ScriptedClient:
    generation_aware = True
    deadline_aware = False

    def __init__(self, fn):
        self.fn = fn
        self.calls = []

    def execute_query(self, node, index, query, slices, remote,
                      gens_out=None, **kwargs):
        self.calls.append((node.host, query, tuple(slices or ())))
        return self.fn(node, index, query, slices, gens_out)


class TestExecutorResize:
    def _setup(self, holder, fn, n_slices=4):
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("f")
        for s in range(n_slices):
            f.set_bit("standard", 1, s * SLICE_WIDTH + 1)
        idx.set_remote_max_slice(n_slices - 1)
        cluster = new_cluster(["local", "peer:1"], replica_n=1)
        client = ScriptedClient(fn)
        e = Executor(holder, host="local", cluster=cluster,
                     client=client, use_mesh=False)
        return e, client, cluster

    def test_remote_leg_fenced_on_migration_target(self, holder):
        """The server-side read fence: a remote (opt.remote) leg for a
        moving slice on a node that is only the TARGET owner fails
        instead of serving the incomplete copy."""
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("f")
        for s in range(4):
            f.set_bit("standard", 1, s * SLICE_WIDTH + 1)
        idx.set_remote_max_slice(3)
        cluster = new_cluster(["a:1", "b:1"], replica_n=1)
        # THIS node is the joining target "local".
        e = Executor(holder, host="local", cluster=cluster,
                     use_mesh=False)
        cluster.install_resize("r1", ["a:1", "b:1", "local"])
        moving = [s for s in range(4)
                  if cluster.moving_slice("i", s) is not None]
        assert moving, "no moving slices in this layout"
        from pilosa_tpu.errors import SliceUnavailableError
        with pytest.raises(SliceUnavailableError):
            e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))',
                      slices=moving, opt=ExecOptions(remote=True))
        # After the flip the same leg serves.
        cluster.flip_epoch("r1")
        res = e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))',
                        slices=moving, opt=ExecOptions(remote=True))
        assert res[0] == len(moving)

    def test_double_read_source_wins(self, holder):
        """Migrating phase: both sides are queried; the old owner's
        answer is authoritative and its tokens merge."""
        def fn(node, index, query, slices, gens_out):
            # Remote peer (old owner) answers its slices.
            return [len(slices)]

        e, client, cluster = self._setup(holder, fn)
        cluster.install_resize("r1", ["local", "peer:1", "new:1"])
        moving = [s for s in range(4)
                  if cluster.moving_slice("i", s) is not None
                  and cluster.moving_slice("i", s)[1] == ("peer:1",)]
        if not moving:
            pytest.skip("no peer-owned moving slice in this layout")
        w0 = obs_metrics.RESIZE_DOUBLE_READS.labels("source").value
        res = e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))')
        assert res[0] == 4  # scripted: every remote slice counts 1
        assert obs_metrics.RESIZE_DOUBLE_READS.labels(
            "source").value > w0

    def test_double_read_target_wins_only_post_flip_shape(self, holder):
        """Old side dead: the target's answer is used only when IT
        accepted the leg (which the fence permits only once the
        target believes the epoch advanced) — and its tokens merge as
        the newest."""
        from pilosa_tpu.cluster import generations as gens_mod
        from pilosa_tpu.cluster.client import ClientError

        def fn(node, index, query, slices, gens_out):
            if node.host == "peer:1":
                raise ClientError("old owner SIGKILLed")
            # the target answers (it has flipped) and piggybacks
            # fresh tokens
            if gens_out is not None:
                payload = gens_mod.encode_wire(
                    index, {s: {"f/standard": (9, 5)} for s in slices})
                gens_out.append((node.host, payload))
            return [len(slices) * 10]

        e, client, cluster = self._setup(holder, fn)
        from pilosa_tpu.cluster.generations import GenerationMap
        e.gens = GenerationMap(staleness_s=60.0)
        cluster.install_resize("r1", ["local", "peer:1", "new:1"])
        moving = [s for s in range(4)
                  if cluster.moving_slice("i", s) is not None
                  and cluster.moving_slice("i", s)[1] == ("peer:1",)]
        if not moving:
            pytest.skip("no peer-owned moving slice in this layout")
        t0 = obs_metrics.RESIZE_DOUBLE_READS.labels("target").value
        # Restrict to the moving slices: the scripted old owner is
        # "dead" for every leg, and non-moving peer slices have no
        # second copy to fail over to.
        res = e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))',
                        slices=moving)
        assert res[0] == 10 * len(moving)
        assert obs_metrics.RESIZE_DOUBLE_READS.labels(
            "target").value == t0 + 1
        # winner tokens merged
        assert e.gens.token("new:1", "i", "f", "standard",
                            moving[0]) == (9, 5)

    def test_double_read_stale_target_tokens_lose(self, holder):
        """Newest-token-wins: a target whose piggybacked generation
        REGRESSED vs the map's knowledge (same uid, lower gen) cannot
        win even when the old side is dead."""
        from pilosa_tpu.cluster import generations as gens_mod
        from pilosa_tpu.cluster.client import ClientError

        def fn(node, index, query, slices, gens_out):
            if node.host == "peer:1":
                raise ClientError("old owner dead")
            if gens_out is not None:
                payload = gens_mod.encode_wire(
                    index, {s: {"f/standard": (9, 1)} for s in slices})
                gens_out.append((node.host, payload))
            return [999]

        e, client, cluster = self._setup(holder, fn)
        from pilosa_tpu.cluster.generations import GenerationMap
        e.gens = GenerationMap(staleness_s=60.0)
        cluster.install_resize("r1", ["local", "peer:1", "new:1"])
        moving = [s for s in range(4)
                  if cluster.moving_slice("i", s) is not None
                  and cluster.moving_slice("i", s)[1] == ("peer:1",)]
        if not moving:
            pytest.skip("no peer-owned moving slice in this layout")
        # The map already saw gen 4 from this target for the slice.
        e.gens.apply("new:1", "i",
                     {moving[0]: {"f/standard": (9, 4)}})
        from pilosa_tpu.cluster.client import ClientError as CE
        with pytest.raises(CE):
            e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))',
                      slices=moving)

    def test_double_read_partial_mode_reports_missing(self, holder):
        """?partial=1 keeps its degraded-read contract during a
        migration: a moving slice with BOTH sides unreachable is
        reported missing instead of failing the query."""
        from pilosa_tpu.cluster.client import ClientError

        def fn(node, index, query, slices, gens_out):
            raise ClientError("everyone is dead")

        e, client, cluster = self._setup(holder, fn)
        cluster.install_resize("r1", ["local", "peer:1", "new:1"])
        moving = [s for s in range(4)
                  if cluster.moving_slice("i", s) is not None
                  and cluster.moving_slice("i", s)[1] == ("peer:1",)]
        if not moving:
            pytest.skip("no peer-owned moving slice in this layout")
        opt = ExecOptions(partial=True)
        res = e.execute("i", 'Count(Bitmap(rowID=1, frame="f"))',
                        slices=moving, opt=opt)
        assert res[0] == 0
        assert sorted(opt.missing_slices) == sorted(moving)

    def test_fast_write_lane_disabled_during_resize(self, holder):
        """The single-node per-op fast lane must fall back to the
        generic (union-fanning) path the moment a resize is
        installed — a 1→2 grow's double-writes depend on it."""
        forwarded = []

        def fn(node, index, query, slices, gens_out):
            forwarded.append((node.host, query))
            return [True]

        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists("f")
        cluster = new_cluster(["local"], replica_n=1)
        client = ScriptedClient(fn)
        e = Executor(holder, host="local", cluster=cluster,
                     client=client, use_mesh=False)
        # Warm the fast lane pre-resize.
        e.execute("i", 'SetBit(frame="f", rowID=1, columnID=1)')
        assert not forwarded
        cluster.install_resize("r1", ["local", "new:1"])
        e.on_resize_change()
        # Find a column whose slice moves (its partition gained new:1).
        target_col = None
        for s in range(16):
            mv = cluster.moving_slice("i", s)
            if mv is not None:
                target_col = s * SLICE_WIDTH + 5
                break
        assert target_col is not None
        e.execute("i", f'SetBit(frame="f", rowID=1,'
                       f' columnID={target_col})')
        assert forwarded, "write did not fan to the union target"

    def test_grace_window_never_keys_on_frozen_local_copy(self, holder):
        """Regression (caught by the end-to-end verify drive): inside
        the post-finalize grace window an old owner still
        write-ACCEPTS a moved slice (owns_fragment is true), but its
        copy stops receiving single-path writes — the cache snapshot
        and result keys must classify the slice by READ authority, or
        the frozen local fragment validates stale results forever."""
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("general")
        from pilosa_tpu.cluster.generations import GenerationMap
        cluster = new_cluster(["local", "peer:1"], replica_n=1)
        gens = GenerationMap(staleness_s=60.0)
        e = Executor(holder, host="local", cluster=cluster,
                     client=ScriptedClient(lambda *a: [0]), gens=gens,
                     use_mesh=False)
        # A slice that moves FROM local TO the joiner.
        cluster.install_resize("g1", ["local", "peer:1", "new:1"])
        moved = next(
            (s for s in range(64)
             if cluster.moving_slice("i", s) is not None
             and cluster.moving_slice("i", s)[1] == ("local",)), None)
        if moved is None:
            pytest.skip("no local-owned moving slice in this layout")
        f.set_bit("standard", 1, moved * SLICE_WIDTH + 3)
        cluster.flip_epoch("g1")
        cluster.finalize_resize("g1", grace_s=60.0)
        # Grace: local still write-accepts, but has NO read authority.
        assert cluster.owns_fragment("local", "i", moved)
        assert not cluster.read_allowed("local", "i", moved)
        # The snapshot must NOT classify the moved slice as local —
        # with no knowledge of the new owner it declines outright.
        assert e._cluster_cache_snapshot("i", [moved]) is None
        # With the serving owner's tokens known, it keys on THEM.
        gens.apply("new:1", "i",
                   {moved: {"general/standard": (9, 4)}})
        snap = e._cluster_cache_snapshot("i", [moved])
        assert snap is not None and moved not in snap["local"]
        assert snap["remote"]["new:1"][moved] == {
            "general/standard": (9, 4)}
        # A FRESHER map entry from a peer with no read authority
        # (e.g. an old owner's frozen copy) must not key an entry.
        gens.apply("peer:1", "i",
                   {moved: {"general/standard": (5, 7)}})
        assert e._cluster_cache_snapshot("i", [moved]) is None
        # Result-residency keys follow the same rule: the moved slice
        # keys on the new owner's tokens, never the frozen local
        # fragment's.
        f.set_bit("standard", 2, moved * SLICE_WIDTH + 4)
        call = parse_pql(
            'Union(Bitmap(rowID=1, frame=general),'
            ' Bitmap(rowID=2, frame=general))').calls[0]
        key = e._bitmap_result_key("i", call, [moved])
        assert key is not None
        gen_entries = key[3]
        assert any(p == "new:1" for p, _u, _g in gen_entries)
        assert all(p != "" for p, _u, _g in gen_entries), \
            "moved slice keyed on the frozen local fragment"

    def test_epoch_bump_invalidates_result_caches(self, holder):
        """ISSUE 12 satellite regression (also in
        test_distributed_fastpath): entries keyed before the flip —
        local-only keys included — never serve after it."""
        must_set(holder, "i", "general", 10, 3)
        must_set(holder, "i", "general", 11, 3)
        # Pinned hasher: every partition's owner is nodes[0] in both
        # memberships, so the epoch can bump with an EMPTY movement
        # set and everything keeps serving locally (the key/flush
        # mechanics are what is under test, not routing).
        cluster = new_cluster(["local"], replica_n=1)
        cluster.hasher = lambda key, n: 0
        e = Executor(holder, host="local", cluster=cluster,
                     use_mesh=False)
        q = ('Union(Bitmap(rowID=10, frame=general),'
             ' Bitmap(rowID=11, frame=general))')
        e.execute("i", q)
        assert e._bitmap_results, "warm-up did not cache"
        key = next(iter(e._bitmap_results))
        assert key[-1] == 0  # epoch in the key
        # During the in-flight resize nothing caches at all.
        cluster.install_resize("r1", ["local", "new:1"])
        e.on_resize_change()
        call = parse_pql(q).calls[0]
        assert e._bitmap_result_key("i", call, [0]) is None
        cluster.flip_epoch("r1")
        # The eager flush drops entries touching moved slices.
        e.on_resize_change(lambda index, s: True)
        assert not e._bitmap_results
        cluster.finalize_resize("r1", grace_s=0.0)
        e.execute("i", q)
        key2 = next(iter(e._bitmap_results))
        assert key2[-1] == 1 and key2 != key


# ---------------------------------------------------------------------------
# in-process coordinator protocol (real Servers, static membership)


def _post(host, path, body=b"{}"):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=30).read()


def _query(host, index, body):
    return json.loads(
        _post(host, f"/index/{index}/query", body.encode()))["results"]


def _get(host, path):
    return json.loads(urllib.request.urlopen(
        f"http://{host}{path}", timeout=10).read())


def _wait_resize(host, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        op = _get(host, "/cluster/resize")["op"]
        if op and op["phase"] in ("done", "aborted"):
            return op
        time.sleep(0.1)
    raise AssertionError("resize did not settle")


@pytest.fixture
def trio(tmp_path, monkeypatch):
    """Three in-process servers: two cross-wired as a static cluster,
    the third booted knowing the CURRENT membership (the join
    candidate), plus seeded data and its dict model."""
    monkeypatch.setenv("PILOSA_TPU_MESH", "0")
    from pilosa_tpu.cluster.client import Client
    from pilosa_tpu.server.server import Server

    servers = []

    def make(name):
        s = Server(str(tmp_path / name), host="127.0.0.1:0",
                   anti_entropy_interval=0, polling_interval=0)
        s.open()
        servers.append(s)
        return s

    s1, s2, s3 = make("n1"), make("n2"), make("n3")
    for s in servers:
        s.cluster.nodes = [Node(s1.host), Node(s2.host)]
    for h in (s1.host, s2.host, s3.host):
        _post(h, "/index/rz")
        _post(h, "/index/rz/frame/f")
    rng = np.random.default_rng(5)
    n_bits = 2000
    rows = rng.integers(0, 8, n_bits).astype(np.uint64)
    cols = rng.choice(6 * SLICE_WIDTH, size=n_bits,
                      replace=False).astype(np.uint64)
    Client(s1.host).import_arrays("rz", "f", rows, cols)
    for s in servers:
        s.holder.index("rz").set_remote_max_slice(5)
    model: dict = {}
    for r, c in zip(rows.tolist(), cols.tolist()):
        model.setdefault(int(r), set()).add(int(c))
    yield servers, model
    failpoints.disarm_all()
    for s in servers:
        try:
            s.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass


def _differential(hosts, model, rows=range(8)):
    for h in hosts:
        for row in rows:
            got = _query(h, "rz",
                         f'Count(Bitmap(frame="f", rowID={row}))')[0]
            assert got == len(model.get(row, set())), (h, row, got)


class TestCoordinatorInProcess:
    def test_grow_under_live_load_zero_wrong_answers(self, trio):
        (s1, s2, s3), model = trio
        stop = threading.Event()
        errors: list = []

        def loadgen():
            i = 0
            while not stop.is_set():
                col = int(6 * SLICE_WIDTH - 1 - i)
                i += 1
                try:
                    _query((s1, s2)[i % 2].host, "rz",
                           f'SetBit(frame="f", rowID=30,'
                           f' columnID={col})')
                    for h in (s1.host, s2.host):
                        got = _query(
                            h, "rz",
                            'Count(Bitmap(frame="f", rowID=2))')[0]
                        if got != len(model[2]):
                            errors.append((h, got, len(model[2])))
                except Exception as e:  # noqa: BLE001 - recorded
                    errors.append(("load", repr(e)))
                time.sleep(0.005)

        t = threading.Thread(target=loadgen)
        t.start()
        try:
            _post(s1.host, "/cluster/resize", json.dumps(
                {"hosts": [s1.host, s2.host, s3.host]}).encode())
            op = _wait_resize(s1.host)
        finally:
            stop.set()
            t.join()
        assert op["phase"] == "done", op
        assert not errors, errors[:5]
        for s in (s1, s2, s3):
            assert s.cluster.epoch == 1
            assert len(s.cluster.nodes) == 3
            assert s.cluster.resize is None
        _differential((s1.host, s2.host, s3.host), model)
        # Concurrent writes (row 30) converged identically everywhere.
        counts = {h: _query(h, "rz",
                            'Count(Bitmap(frame="f", rowID=30))')[0]
                  for h in (s1.host, s2.host, s3.host)}
        assert len(set(counts.values())) == 1, counts
        # The joiner genuinely owns slices now.
        assert any(s3.cluster.owns_fragment(s3.host, "rz", s)
                   for s in range(6))
        assert op["slicesMoved"] >= 1
        assert op["bytesStreamed"] > 0

    @pytest.mark.chaos
    def test_torn_stream_aborts_then_retry_succeeds(self, trio):
        (s1, s2, s3), model = trio
        failpoints.arm("resize.stream", "torn(48)")
        _post(s1.host, "/cluster/resize", json.dumps(
            {"hosts": [s1.host, s2.host, s3.host]}).encode())
        op = _wait_resize(s1.host)
        failpoints.disarm_all()
        assert op["phase"] == "aborted"
        assert "resize.stream" in (op["error"] or "")
        for s in (s1, s2, s3):
            assert s.cluster.epoch == 0
            assert s.cluster.resize is None
            assert len(s.cluster.nodes) == 2
        # The torn prefixes on the target are harmless orphans: the
        # old epoch answers exactly.
        _differential((s1.host, s2.host), model)
        # Retry converges (idempotent block re-diff).
        _post(s1.host, "/cluster/resize", json.dumps(
            {"hosts": [s1.host, s2.host, s3.host]}).encode())
        op = _wait_resize(s1.host)
        assert op["phase"] == "done", op
        _differential((s1.host, s2.host, s3.host), model)

    @pytest.mark.chaos
    def test_intermittent_stream_errors_survive(self, trio):
        """error(p)*N injection: the pass that hits the fault aborts
        nothing by itself — the coordinator retries passes; once the
        budget disarms, the resize completes."""
        (s1, s2, s3), model = trio
        failpoints.arm("resize.stream", "error*2")
        _post(s1.host, "/cluster/resize", json.dumps(
            {"hosts": [s1.host, s2.host, s3.host]}).encode())
        op = _wait_resize(s1.host)
        failpoints.disarm_all()
        # Two injected errors abort the FIRST attempt only if they
        # exhaust it; either way the cluster is consistent.
        if op["phase"] == "aborted":
            for s in (s1, s2, s3):
                assert s.cluster.epoch == 0
            _differential((s1.host, s2.host), model)
        else:
            _differential((s1.host, s2.host, s3.host), model)

    @pytest.mark.chaos
    def test_operator_abort_mid_stream_stops_the_coordinator(self, trio):
        """Review regression: an operator abort must CANCEL the live
        run loop, not just broadcast — otherwise the coordinator
        thread keeps driving and can complete a resize the operator
        was told is aborted."""
        (s1, s2, s3), model = trio
        # The fixture's data holds one checksum block per fragment, so
        # the whole stream is one long delay hit — abort lands inside
        # it (phase "streaming" is enough; bytes only appear after the
        # block completes).
        failpoints.arm("resize.stream", "delay(700ms)")
        _post(s1.host, "/cluster/resize", json.dumps(
            {"hosts": [s1.host, s2.host, s3.host]}).encode())
        deadline = time.time() + 30
        while time.time() < deadline:
            op = _get(s1.host, "/cluster/resize")["op"] or {}
            if op.get("phase") == "streaming":
                break
            if op.get("phase") in ("done", "aborted"):
                pytest.skip("stream window closed before the abort")
            time.sleep(0.02)
        _post(s1.host, "/cluster/resize",
              json.dumps({"abort": True}).encode())
        op = _wait_resize(s1.host)
        failpoints.disarm_all()
        assert op["phase"] == "aborted", op
        # The run thread must not resurrect it afterwards.
        time.sleep(1.0)
        assert _get(s1.host,
                    "/cluster/resize")["op"]["phase"] == "aborted"
        for s in (s1, s2, s3):
            assert s.cluster.epoch == 0
            assert s.cluster.resize is None
        _differential((s1.host, s2.host), model)
        # A fresh resize (new id) still goes through afterwards.
        _post(s1.host, "/cluster/resize", json.dumps(
            {"hosts": [s1.host, s2.host, s3.host]}).encode())
        assert _wait_resize(s1.host)["phase"] == "done"
        _differential((s1.host, s2.host, s3.host), model)

    @pytest.mark.chaos
    def test_partition_during_epoch_flip(self, trio):
        """The flip-window chaos leg: the coordinator drives the
        protocol up to the flip, then a one-way partition cuts the
        joining target off the control plane — s1 and s2 flip, s3
        cannot. Differential queries DURING the mixed-epoch window
        must stay exact (flipped nodes route moved slices to the
        target, the partitioned leg fails, the failover re-map serves
        from the still-read-valid old owner), and once the partition
        heals the flip completes cluster-wide."""
        import threading as threading_mod

        from pilosa_tpu.server.syncer import FragmentStreamer
        (s1, s2, s3), model = trio
        coord = resize_mod.ResizeCoordinator(
            s1, [s1.host, s2.host, s3.host])
        coord.moving = movement(
            coord.old_hosts, coord.target_hosts,
            s1.cluster.partition_n, s1.cluster.replica_n)
        coord.journal.write(id=coord.id, epochFrom=0,
                            old=coord.old_hosts,
                            new=coord.target_hosts,
                            coordinator=s1.host)
        coord._set_phase(resize_mod.PHASE_PREPARING)
        coord._send_phase(coord._message("prepare"),
                          coord._union_hosts(), require_all=True)
        coord._sync_slice_knowledge()
        streamer = FragmentStreamer(
            client_factory=s1._client_factory,
            on_block=coord._on_stream_block)
        coord._set_phase(resize_mod.PHASE_STREAMING)
        for _ in range(resize_mod.MAX_STREAM_PASSES):
            if coord._stream_pass(streamer) == 0:
                break
        # One-way partition: nothing from this process reaches s3.
        failpoints.arm("rpc.send", f"partition({s3.host})")
        flip_err: list = []

        def do_flip():
            try:
                coord._set_phase(resize_mod.PHASE_FLIPPING)
                coord._send_phase(coord._message("flip"),
                                  coord._union_hosts(),
                                  require_all=True, retries=60)
            except Exception as e:  # noqa: BLE001 - recorded
                flip_err.append(e)

        t = threading_mod.Thread(target=do_flip)
        t.start()
        # The mixed-epoch window: s1 + s2 flipped, s3 fenced out.
        deadline = time.time() + 10
        while time.time() < deadline and not (
                s1.cluster.epoch == 1 and s2.cluster.epoch == 1):
            time.sleep(0.05)
        assert s1.cluster.epoch == 1 and s2.cluster.epoch == 1
        assert s3.cluster.epoch == 0  # partitioned: not yet flipped
        # Differential-checked queries INSIDE the window, from both
        # flipped coordinators: moved-slice legs to the unflipped
        # target fail (partition + read fence) and fail over to the
        # old owner, whose draining copy is complete — answers exact.
        for _ in range(3):
            _differential((s1.host, s2.host), model)
        # Heal the partition: the flip completes cluster-wide.
        failpoints.disarm_all()
        t.join(timeout=60)
        assert not t.is_alive() and not flip_err, flip_err
        assert s3.cluster.epoch == 1
        coord._set_phase(resize_mod.PHASE_DRAINING)
        coord._stream_pass(streamer)
        coord._set_phase(resize_mod.PHASE_FINALIZING)
        coord._send_phase(coord._message("finalize"),
                          coord._union_hosts(), require_all=False)
        coord._set_phase(resize_mod.PHASE_DONE)
        for s in (s1, s2, s3):
            assert s.cluster.resize is None and s.cluster.epoch == 1
        _differential((s1.host, s2.host, s3.host), model)

    def test_shrink_back(self, trio):
        (s1, s2, s3), model = trio
        _post(s1.host, "/cluster/resize", json.dumps(
            {"hosts": [s1.host, s2.host, s3.host]}).encode())
        assert _wait_resize(s1.host)["phase"] == "done"
        _post(s2.host, "/cluster/resize",
              json.dumps({"remove": s3.host}).encode())
        op = _wait_resize(s2.host)
        assert op["phase"] == "done", op
        for s in (s1, s2):
            assert s.cluster.epoch == 2
            assert len(s.cluster.nodes) == 2
        _differential((s1.host, s2.host), model)

    def test_one_resize_at_a_time(self, trio):
        (s1, s2, s3), _model = trio
        s1.cluster.install_resize("blocker", [s1.host, s2.host,
                                              "x:1"])
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(s1.host, "/cluster/resize", json.dumps(
                {"hosts": [s1.host, s2.host, s3.host]}).encode())
        assert ei.value.code == 409
        s1.cluster.abort_resize("blocker")

    def test_journal_recovery_pre_flip_aborts(self, trio):
        """A coordinator that died mid-STREAMING aborts back to the
        old epoch on recovery — and the abort broadcast clears the
        peers' installed state."""
        (s1, s2, s3), model = trio
        # Simulate the crashed coordinator: peers installed the
        # resize, the journal says streaming, nobody is driving.
        msg = ResizeMessage(id="crashed", phase="prepare", epoch=0,
                            old_hosts=[s1.host, s2.host],
                            new_hosts=[s1.host, s2.host, s3.host])
        for s in (s1, s2, s3):
            s.receive_message(msg)
        assert all(s.cluster.resize is not None for s in (s1, s2, s3))
        j = resize_mod.ResizeJournal.for_data_dir(s1.holder.path)
        j.write(id="crashed", phase=resize_mod.PHASE_STREAMING,
                epochFrom=0, old=[s1.host, s2.host],
                new=[s1.host, s2.host, s3.host], coordinator=s1.host)
        status = resize_mod.recover(s1)
        assert status is not None
        assert status["phase"] == resize_mod.PHASE_ABORTED
        for s in (s1, s2, s3):
            assert s.cluster.resize is None
            assert s.cluster.epoch == 0
        _differential((s1.host, s2.host), model)
        # The journal records the acked abort: nothing left in flight.
        j2 = resize_mod.ResizeJournal.for_data_dir(s1.holder.path)
        j2.load()
        assert not j2.in_flight()

    def test_journal_recovery_post_flip_rolls_forward(self, trio):
        """A coordinator that died after sending ANY flip rolls the
        resize forward: flip is re-sent (nodes that lost state install
        from the message), the drain diff runs, finalize lands."""
        (s1, s2, s3), model = trio
        prep = ResizeMessage(id="flipped", phase="prepare", epoch=0,
                             old_hosts=[s1.host, s2.host],
                             new_hosts=[s1.host, s2.host, s3.host])
        for s in (s1, s2, s3):
            s.receive_message(prep)
        # Pretend the crash happened mid-flip: only s2 processed it.
        flip = ResizeMessage(id="flipped", phase="flip", epoch=0,
                             old_hosts=[s1.host, s2.host],
                             new_hosts=[s1.host, s2.host, s3.host])
        s2.receive_message(flip)
        assert s2.cluster.epoch == 1 and s1.cluster.epoch == 0
        j = resize_mod.ResizeJournal.for_data_dir(s1.holder.path)
        j.write(id="flipped", phase=resize_mod.PHASE_FLIPPING,
                epochFrom=0, old=[s1.host, s2.host],
                new=[s1.host, s2.host, s3.host], coordinator=s1.host)
        status = resize_mod.recover(s1)
        assert status is not None
        assert status["phase"] == resize_mod.PHASE_DONE, status
        for s in (s1, s2, s3):
            assert s.cluster.epoch == 1
            assert len(s.cluster.nodes) == 3
            assert s.cluster.resize is None
        _differential((s1.host, s2.host, s3.host), model)

    def test_debug_topology_and_metrics(self, trio):
        (s1, s2, s3), _model = trio
        topo = _get(s1.host, "/debug/topology")
        assert topo["epoch"] == 0
        assert sorted(topo["nodes"]) == sorted([s1.host, s2.host])
        assert topo["resize"] is None
        assert "rz" in topo["indexes"]
        owners = topo["indexes"]["rz"]["owners"]
        assert set(owners) == {str(s) for s in range(6)}
        # In-flight state surfaces (install a resize by hand).
        s1.cluster.install_resize("t1", [s1.host, s2.host, s3.host])
        topo = _get(s1.host, "/debug/topology")
        assert topo["resize"]["id"] == "t1"
        assert topo["resize"]["phase"] == "migrating"
        moving = topo["indexes"]["rz"].get("movingSlices", [])
        assert moving, "no moving slices reported"
        s1.cluster.abort_resize("t1")
        # Metric families exist and render.
        text = urllib.request.urlopen(
            f"http://{s1.host}/metrics", timeout=10).read().decode()
        for fam in ("pilosa_cluster_resize_state",
                    "pilosa_resize_slices_moved_total",
                    "pilosa_resize_stream_bytes_total",
                    "pilosa_cluster_resize_double_reads_total"):
            assert fam in text, fam

    def test_watchdog_resize_stall_cause(self, trio):
        """A coordinator whose active phase stops progressing trips
        the watchdog's resize_stall cause."""
        from pilosa_tpu.obs.watchdog import Watchdog
        (s1, s2, s3), _model = trio
        coord = resize_mod.ResizeCoordinator(
            s1, [s1.host, s2.host, s3.host])
        coord.phase = resize_mod.PHASE_STREAMING
        coord.last_progress = time.monotonic() - 100.0
        s1.resize_op = coord
        wd = Watchdog(resize_progress_fn=s1._resize_progress,
                      resize_stall_s=5.0, wal_stall_s=0,
                      deadline_grace_s=0, gossip_silence_s=0,
                      queue_stall_s=0)
        fired = wd.check()
        assert any(c == "resize_stall" for c, _ in fired), fired
        assert obs_metrics.WATCHDOG_TRIPS.labels(
            "resize_stall").value >= 1
        s1.resize_op = None

    def test_blackbox_state_has_resize_block(self, trio):
        (s1, s2, s3), _model = trio
        state = s1._blackbox_state()
        assert state["resize"]["epoch"] == 0
        assert state["resize"]["inFlight"] is None
        s1.cluster.install_resize("bb", [s1.host, s2.host, s3.host])
        state = s1._blackbox_state()
        assert state["resize"]["inFlight"]["id"] == "bb"
        s1.cluster.abort_resize("bb")

    def test_anti_entropy_skips_moving_fragments(self, trio):
        """The syncer must leave moving fragments to the streamer — a
        consensus merge with an incomplete target could clear
        not-yet-streamed bits."""
        from pilosa_tpu.server.syncer import HolderSyncer
        (s1, s2, s3), model = trio
        s1.cluster.install_resize("ae", [s1.host, s2.host, s3.host])
        synced = []

        class SpyingSyncer(HolderSyncer):
            def sync_fragment(self, index, frame, view, slice):
                synced.append(slice)

        SpyingSyncer(s1.holder, s1.host, s1.cluster,
                     client_factory=s1._client_factory).sync_holder()
        moving = {s for s in range(6)
                  if s1.cluster.moving_slice("rz", s) is not None}
        assert moving
        assert not (set(synced) & moving), (synced, moving)
        s1.cluster.abort_resize("ae")
