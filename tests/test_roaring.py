"""Roaring bitmap engine tests.

Models the reference's test strategy (SURVEY.md §4): randomized
add/remove/contains property tests (reference roaring/roaring_test.go:182-249)
and marshal round-trips including write→load→mutate
(roaring_test.go:250-314), plus container-boundary and op-log cases.
"""

import io
import random

import numpy as np
import pytest

from pilosa_tpu.storage import native, roaring
from pilosa_tpu.storage.roaring import ARRAY_MAX_SIZE, Bitmap, Op


def rand_values(rng, n, lo=0, hi=1 << 40):
    return sorted(rng.sample(range(lo, hi), n))


class TestContainerBoundaries:
    def test_array_to_bitmap_conversion(self):
        b = Bitmap()
        for v in range(ARRAY_MAX_SIZE + 1):
            assert b.add(v * 2)
        c = b.container(0)
        assert not c.is_array()
        assert c.n == ARRAY_MAX_SIZE + 1
        b.check()

    def test_bitmap_to_array_conversion(self):
        b = Bitmap()
        vals = list(range(ARRAY_MAX_SIZE + 2))
        for v in vals:
            b.add(v)
        assert not b.container(0).is_array()
        b.remove(vals[0])
        assert not b.container(0).is_array()  # n == 4097 still bitmap
        b.remove(vals[1])
        assert b.container(0).is_array()      # n == 4096 → array
        b.check()
        assert b.count() == ARRAY_MAX_SIZE

    def test_add_remove_contains(self):
        b = Bitmap()
        assert b.add(65537)
        assert not b.add(65537)
        assert b.contains(65537)
        assert not b.contains(65536)
        assert b.remove(65537)
        assert not b.remove(65537)
        assert b.count() == 0


class TestQuick:
    """Randomized property test vs a Python set (roaring_test.go:182-249)."""

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_add_remove_quick(self, seed):
        rng = random.Random(seed)
        b = Bitmap()
        model = set()
        for _ in range(2000):
            v = rng.randrange(0, 1 << 34)
            if rng.random() < 0.7:
                assert b.add(v) == (v not in model)
                model.add(v)
            else:
                assert b.remove(v) == (v in model)
                model.discard(v)
        assert b.count() == len(model)
        got = set(int(x) for x in b.values())
        assert got == model
        b.check()

    def test_dense_container_quick(self):
        rng = random.Random(42)
        b = Bitmap()
        model = set()
        # Stay inside two containers to force bitmap representation.
        for _ in range(12000):
            v = rng.randrange(0, 2 << 16)
            b.add(v)
            model.add(v)
        for _ in range(3000):
            v = rng.randrange(0, 2 << 16)
            assert b.remove(v) == (v in model)
            model.discard(v)
        assert set(int(x) for x in b.values()) == model
        b.check()


class TestBulk:
    def test_add_many_matches_loop(self):
        rng = random.Random(7)
        vals = rand_values(rng, 5000, hi=1 << 30)
        a = Bitmap()
        a.add_many(np.array(vals, dtype=np.uint64))
        c = Bitmap(*vals)
        assert np.array_equal(a.values(), c.values())
        a.check()

    def test_add_many_merges_into_existing(self):
        b = Bitmap(1, 100, 65536)
        b.add_many(np.array([1, 2, 65537], dtype=np.uint64))
        assert sorted(int(x) for x in b.values()) == [1, 2, 100, 65536, 65537]

    def test_remove_many_matches_loop(self):
        rng = random.Random(11)
        vals = rand_values(rng, 8000, hi=1 << 20)  # dense → bitmap blocks
        b = Bitmap(*vals)
        drop = vals[::3] + [999999999, 12345678]  # incl. absent values
        n = b.remove_many(np.array(drop, dtype=np.uint64))
        model = set(vals) - set(drop)
        assert n == len(set(vals)) - len(model)
        assert set(int(x) for x in b.values()) == model
        b.check()

    def test_remove_many_converts_bitmap_to_array(self):
        vals = list(range(6000))  # one bitmap container
        b = Bitmap(*vals)
        assert not b.containers[0].is_array()
        b.remove_many(np.arange(5000, dtype=np.uint64))
        assert b.containers[0].is_array()  # n=1000 ≤ 4096 → array block
        assert set(int(x) for x in b.values()) == set(range(5000, 6000))
        b.check()

    def test_remove_many_duplicate_values_clear_once(self):
        b = Bitmap(1, 2, 3)
        n = b.remove_many(np.array([2, 2, 2], dtype=np.uint64))
        assert n == 1
        assert sorted(int(x) for x in b.values()) == [1, 3]

    def test_count_range_and_slice_range(self):
        vals = [0, 1, 100, 65535, 65536, 1 << 20, (1 << 20) + 5]
        b = Bitmap(*vals)
        assert b.count_range(0, 1 << 30) == len(vals)
        assert b.count_range(1, 65536) == 3  # {1, 100, 65535}
        assert b.count_range(65536, 65537) == 1
        assert list(b.slice_range(1, 65537)) == [1, 100, 65535, 65536]
        assert b.count_range(5, 5) == 0


class TestSetAlgebra:
    @pytest.mark.parametrize("seed,na,nb,hi", [
        (1, 100, 100, 1 << 18),       # array∩array
        (2, 6000, 100, 1 << 17),      # bitmap∩array
        (3, 9000, 9000, 1 << 17),     # bitmap∩bitmap
        (4, 500, 8000, 1 << 20),      # mixed keys
    ])
    def test_ops_match_sets(self, seed, na, nb, hi):
        rng = random.Random(seed)
        av, bv = set(rng.sample(range(hi), na)), set(rng.sample(range(hi), nb))
        a, b = Bitmap(*sorted(av)), Bitmap(*sorted(bv))
        assert set(map(int, a.intersect(b).values())) == av & bv
        assert set(map(int, a.union(b).values())) == av | bv
        assert set(map(int, a.difference(b).values())) == av - bv
        assert set(map(int, a.xor(b).values())) == av ^ bv
        assert a.intersection_count(b) == len(av & bv)
        for r in (a.intersect(b), a.union(b), a.difference(b), a.xor(b)):
            r.check()

    def test_ops_do_not_mutate_inputs(self):
        a, b = Bitmap(1, 2, 3), Bitmap(2, 3, 4)
        u = a.union(b)
        u.add(99)
        d = a.difference(b)
        d.add(98)
        assert set(map(int, a.values())) == {1, 2, 3}
        assert set(map(int, b.values())) == {2, 3, 4}


class TestOffsetRange:
    def test_offset_range_basic(self):
        sw = 1 << 20
        b = Bitmap(1, 65536, sw - 1, sw, sw + 10)
        row = b.offset_range(0, 0, sw)  # row 0 of a slice-width row space
        assert list(map(int, row.values())) == [1, 65536, sw - 1]
        row1 = b.offset_range(0, sw, 2 * sw)
        assert list(map(int, row1.values())) == [0, 10]
        shifted = b.offset_range(3 * sw, sw, 2 * sw)
        assert list(map(int, shifted.values())) == [3 * sw, 3 * sw + 10]

    def test_offset_range_copy_on_write(self):
        b = Bitmap(5, 6)
        row = b.offset_range(0, 0, 1 << 20)
        row.add(7)
        assert not b.contains(7)
        b.add(8)
        assert not row.contains(8)

    def test_unaligned_raises(self):
        with pytest.raises(ValueError):
            Bitmap().offset_range(1, 0, 1 << 20)


class TestSerialization:
    def roundtrip(self, b):
        data = b.marshal()
        return Bitmap.unmarshal(data), data

    def test_empty(self):
        b2, data = self.roundtrip(Bitmap())
        assert b2.count() == 0
        assert len(data) == 8

    def test_array_and_bitmap_containers(self):
        rng = random.Random(9)
        vals = (rand_values(rng, 50, hi=1 << 16)
                + rand_values(rng, 6000, lo=1 << 16, hi=2 << 16)
                + [1 << 40])
        b = Bitmap(*sorted(set(vals)))
        b2, data = self.roundtrip(b)
        assert np.array_equal(b.values(), b2.values())
        b2.check()
        # Header layout spot-checks (reference roaring.go:475-533).
        assert int.from_bytes(data[0:4], "little") == roaring.COOKIE
        assert int.from_bytes(data[4:8], "little") == 3  # container count

    def test_mapped_load_then_mutate(self):
        """write → load zero-copy → mutate must not touch the buffer
        (reference roaring_test.go marshal-mutate cases)."""
        b = Bitmap(*range(0, 10000, 3))
        data = bytearray(b.marshal())
        b2 = Bitmap.unmarshal(data, mapped=True)
        before = bytes(data)
        b2.add(1)
        b2.remove(3)
        assert bytes(data) == before
        assert b2.contains(1) and not b2.contains(3)
        b2.check()

    def test_oplog_replay(self):
        b = Bitmap(10, 20)
        data = b.marshal()
        ops = (Op(roaring.OP_ADD, 30).marshal()
               + Op(roaring.OP_REMOVE, 10).marshal()
               + Op(roaring.OP_ADD, 1 << 33).marshal())
        b2 = Bitmap.unmarshal(data + ops)
        assert set(map(int, b2.values())) == {20, 30, 1 << 33}
        assert b2.op_n == 3

    def test_corrupt_key_count_rejected(self):
        data = bytearray(Bitmap(1, 2, 3).marshal())
        data[4:8] = (1000).to_bytes(4, "little")  # lie about container count
        with pytest.raises(ValueError, match="header out of bounds"):
            Bitmap.unmarshal(data)

    def test_oplog_corruption_detected(self):
        b = Bitmap(10)
        data = b.marshal() + Op(roaring.OP_ADD, 30).marshal()
        corrupted = bytearray(data)
        corrupted[-6] ^= 0xFF  # flip a bit inside the op value
        with pytest.raises(ValueError, match="checksum"):
            Bitmap.unmarshal(corrupted)

    def test_op_writer(self):
        log = io.BytesIO()
        b = Bitmap()
        b.op_writer = log
        b.add(42)
        b.add(42)  # no-op: must not log
        b.remove(42)
        raw = log.getvalue()
        assert len(raw) == 2 * roaring.OP_SIZE
        op = Op.unmarshal(memoryview(raw))
        assert op.typ == roaring.OP_ADD and op.value == 42

    def test_cross_container_kinds_survive_roundtrip(self):
        # A container written as bitmap must come back as bitmap (n>4096).
        b = Bitmap(*range(5000))
        b2, _ = self.roundtrip(b)
        assert not b2.container(0).is_array()
        # After removals under threshold, write→read flips it to array.
        for v in range(1000):
            b2.remove(v)
        b3, _ = self.roundtrip(b2)
        assert b3.container(0).is_array()
        assert b3.count() == 4000


class TestNative:
    def test_native_matches_numpy(self):
        rng = np.random.default_rng(0)
        a = rng.integers(0, 1 << 64, 4096, dtype=np.uint64)
        b = rng.integers(0, 1 << 64, 4096, dtype=np.uint64)
        assert native.popcnt_and(a, b) == int(np.bitwise_count(a & b).sum())
        assert native.popcnt_or(a, b) == int(np.bitwise_count(a | b).sum())
        assert native.popcnt_xor(a, b) == int(np.bitwise_count(a ^ b).sum())
        assert native.popcnt_andnot(a, b) == int(
            np.bitwise_count(a & ~b).sum())

    def test_native_library_builds(self):
        # The toolchain is part of the environment contract; if this fails
        # the numpy fallback hides a build regression, so assert directly.
        assert native.available()

    def test_pack_unpack_roundtrip(self):
        sw = 1 << 20
        wpr = sw // 32
        pos = np.array([0, 31, 32, sw - 1, sw, 2 * sw + 77], dtype=np.uint64)
        words = np.zeros((3, wpr), dtype=np.uint32)
        native.pack_positions(pos, sw, wpr, words)
        got = []
        for r in range(3):
            cols = native.unpack_words(words[r])
            got.extend(r * sw + int(c) for c in cols)
        assert got == list(map(int, pos))
