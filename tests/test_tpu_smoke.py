"""Opt-in real-chip smoke test (VERDICT r1 weak #5: the only TPU
exercise in round 1 was bench.py, which crashed — a cheap on-chip
canary would have caught it).

Skipped by default: the CI suite pins a virtual-CPU JAX
(tests/conftest.py), and the axon TPU tunnel can hang for minutes when
down. Set PILOSA_TPU_SMOKE=1 to run — the chip work happens in a
bounded subprocess with the conftest's CPU pin stripped, so a wedged
tunnel fails the test instead of hanging the suite.

Covers the kernels the serving path dispatches on TPU: the fused
op_count (bench.py's kernel), the Pallas expression-count program, the
Pallas TopN block program, and the sparse-upload densify kernel
(compiled lowering — interpret-mode CI cannot catch Mosaic tiling or
scalar-store rejections; three round-4 densify designs died only at
compile time on the real chip).
"""

import os
import subprocess
import sys

import pytest

_CHILD = r"""
import numpy as np, jax
from pilosa_tpu.ops.kernels import op_count
from pilosa_tpu.parallel import mesh as mesh_mod

assert jax.devices()[0].platform == "tpu", jax.devices()
rng = np.random.default_rng(0)
S, R, W = 9, 5, 2048  # odd sizes: the shapes Mosaic tiling rejects
leaves = rng.integers(0, 2**32, size=(2, S, W), dtype=np.uint32)
rows = rng.integers(0, 2**32, size=(S, R, W), dtype=np.uint32)

got = int(np.asarray(op_count("and", leaves[0], leaves[1])).sum())
want = int(np.bitwise_count(leaves[0] & leaves[1]).sum())
assert got == want, ("op_count", got, want)

m = mesh_mod.make_mesh(1)
expr = ("and", ("leaf", 0), ("leaf", 1))
assert mesh_mod.count_expr(m, expr, leaves) == want

got = mesh_mod.topn_exact(m, ("leaf", 0), rows, leaves[:1])
want_t = np.bitwise_count(rows & leaves[0][:, None, :]) \
    .sum(axis=(0, 2)).tolist()
assert got == want_t, ("topn", got, want_t)

# Compiled densify (the sparse-upload kernel): odd T, G=2 buckets.
from pilosa_tpu.ops.pallas_kernels import densify_pallas
T, subs = 11, 2048 // 128
lane = rng.integers(0, 128, (T, subs, 2)).astype(np.uint32)
val = rng.integers(0, 2**32, (T, subs, 2), dtype=np.uint32)
dense = np.asarray(densify_pallas(lane, val, 2048))
want_d = np.zeros((T, 2048), np.uint32)
for t in range(T):
    for sb in range(subs):
        for g in range(2):
            if val[t, sb, g]:
                want_d[t, sb * 128 + lane[t, sb, g]] |= val[t, sb, g]
assert (dense == want_d).all(), "densify"
print("TPU_SMOKE_OK", jax.devices()[0])
"""


@pytest.mark.skipif(os.environ.get("PILOSA_TPU_SMOKE") != "1",
                    reason="real-chip smoke is opt-in"
                           " (PILOSA_TPU_SMOKE=1)")
def test_real_chip_serving_kernels():
    env = dict(os.environ)
    # Undo the conftest's CPU pin for the child. The axon PJRT plugin
    # registers as an *experimental* platform — JAX only selects it
    # when explicitly named, so point JAX_PLATFORMS back at it.
    if "PALLAS_AXON_POOL_IPS" in env:
        env["JAX_PLATFORMS"] = "axon"
    else:
        env.pop("JAX_PLATFORMS", None)  # generic TPU image: autodetect
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["PILOSA_TPU_PALLAS"] = "1"  # opt in: smoke the compiled Pallas path
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Prepend the repo, preserving the ambient PYTHONPATH — the axon
    # plugin's sitecustomize lives there and must load at startup.
    prior = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = repo + (os.pathsep + prior if prior else "")
    proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                          timeout=600, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "TPU_SMOKE_OK" in proc.stdout, proc.stdout
