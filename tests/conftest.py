"""Test configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding
(`shard_map` over a `jax.sharding.Mesh`) compiles and executes without TPU
hardware. Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
