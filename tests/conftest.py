"""Test configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding
(`shard_map` over a `jax.sharding.Mesh`) compiles and executes without TPU
hardware, and so the suite never touches the shared TPU tunnel.

The environment's axon PJRT plugin (sitecustomize) force-selects the axon
platform via jax.config at register time — which overrides JAX_PLATFORMS —
so we must override back through jax.config, before any backend initializes.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses
# Deterministic routing in tests: the calibrated device/host cost model
# measures THIS machine and could veto device paths that device-path
# tests assert engage. Cost-model behavior is tested explicitly with
# injected calibrations (tests/test_costmodel.py).
os.environ.setdefault("PILOSA_TPU_COST_MODEL", "0")
# Cold-start warmup compiles XLA programs on every Server.open — fine
# for one real server, a tax on the dozens the suite spawns. Warmup
# behavior is tested explicitly (tests/test_sched.py enables it).
os.environ.setdefault("PILOSA_TPU_WARMUP", "0")
# Servers arm the persistent XLA compile cache under their data dir —
# real servers want it, but the suite's servers live in tmp dirs that
# are deleted mid-process (jax.config is process-global, so the FIRST
# server's dir would stick for the whole run). Cache behavior is
# tested explicitly in subprocesses (tests/test_programs.py).
os.environ.setdefault("PILOSA_TPU_COMPILE_CACHE", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # Build the one-crossing mutate extension (storage/native_ext) once
    # at session start so the FIRST fragment test doesn't pay the
    # compile inside its own timing/timeout budget. Graceful: a missing
    # toolchain (or PILOSA_TPU_NATIVE_EXT=0) latches to the pure-Python
    # paths, and tests/test_write_path.py::test_extension_loaded is the
    # tier-1 assertion that the build actually happened where expected.
    from pilosa_tpu.storage import native_ext
    native_ext.load()
    # Marker registry (no pytest.ini in this repo): `slow` is what the
    # tier-1 gate excludes (`-m 'not slow'`); `chaos` tags the
    # failpoint/fault-injection tests — the fast ones run in tier-1,
    # the multi-process SIGKILL cluster legs are additionally `slow`.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 gate (multi-process"
                   " cluster legs, soaks)")
    config.addinivalue_line(
        "markers", "chaos: failpoint-driven fault-injection tests;"
                   " schedules replay from PILOSA_FAULT_SEED")
    config.addinivalue_line(
        "markers", "resize: elastic cluster-resize tests (ISSUE 12) —"
                   " fast failpoint legs run tier-1, the multi-process"
                   " SIGKILL legs are additionally `slow`")
    config.addinivalue_line(
        "markers", "tenant: multi-tenant QoS tests (ISSUE 14) — "
                   "per-tenant lanes/quotas/kill-policy/cache-quota"
                   " units run tier-1, the real 2-node gossip legs"
                   " are additionally `slow`")
    config.addinivalue_line(
        "markers", "scrub: storage-integrity tests (ISSUE 15) — "
                   "footer/scrub/quarantine/repair units run tier-1,"
                   " the real 3-node bit-flip chaos legs are"
                   " additionally `slow`")
    config.addinivalue_line(
        "markers", "tier: tiered-storage tests (ISSUE 16) — "
                   "demotion/faulting/blob/eviction/prefetch units and"
                   " fast failpoint legs run tier-1, the SIGKILL crash"
                   " legs and soaks are additionally `slow`")
    config.addinivalue_line(
        "markers", "replay: workload capture/replay/shadow tests"
                   " (ISSUE 19) — digest/redaction/ring/export units"
                   " run tier-1, the real 2-node merged-export replay"
                   " leg is additionally `slow`")
    config.addinivalue_line(
        "markers", "backup: disaster-recovery tests (ISSUE 20) — "
                   "archive/journal/retention/walarchive units and"
                   " the in-process backup→destroy→restore legs run"
                   " tier-1, the SIGKILL coordinator-crash legs are"
                   " additionally `slow`")
