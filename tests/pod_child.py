"""Child process for the pod end-to-end test (tests/test_pod.py).

Runs one pod process of a 2-process CPU pod (gloo collectives). The
launcher passes the whole env contract; this script only builds a
Server, and — on the coordinator — drives PQL through the full
HTTP → executor → pod broadcast → mesh-collective stack and checks
pod-wide results, mirroring the reference's whole-process cluster tests
(server/server_test.go:375-496).

Usage: python pod_child.py <proc_id> <data_dir>
"""

import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)

from podenv import child_main, http, query  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402
from pilosa_tpu.server.server import Server  # noqa: E402


def main() -> None:
    proc_id = int(sys.argv[1])
    data_dir = sys.argv[2]
    host = os.environ["PILOSA_TPU_POD_PEERS"].split(",")[proc_id]

    srv = Server(data_dir, host=host, anti_entropy_interval=0,
                 polling_interval=0)
    srv.open()
    print(f"pod process {proc_id} serving on {srv.host}", flush=True)

    if proc_id != 0:
        # Worker: serve pod legs until the launcher kills us.
        while True:
            time.sleep(0.5)

    coord = srv.host
    http("POST", coord, "/index/i", b"{}")
    http("POST", coord, "/index/i/frame/f", b"{}")

    # Bits across 4 slices: pod of 2 procs → proc 0 owns slices 0 & 2,
    # proc 1 owns slices 1 & 3 (round-robin placement, parallel.pod).
    # Row 1: 3 bits per slice; row 2: the first 2 of those; row 3: 1.
    for s in range(4):
        for j in range(3):
            query(coord, "i", f"SetBit(frame=f, rowID=1,"
                              f" columnID={s * SLICE_WIDTH + j})")
        for j in range(2):
            query(coord, "i", f"SetBit(frame=f, rowID=2,"
                              f" columnID={s * SLICE_WIDTH + j})")
        query(coord, "i", f"SetBit(frame=f, rowID=3,"
                          f" columnID={s * SLICE_WIDTH})")

    # Pod-wide Count through the device-collective path (all 4 slices,
    # psum across both processes' chips).
    got = query(coord, "i", "Count(Bitmap(frame=f, rowID=1))")[0]
    assert got == 12, f"Count(row1): {got} != 12"
    got = query(coord, "i", "Count(Intersect(Bitmap(frame=f, rowID=1),"
                            " Bitmap(frame=f, rowID=2)))")[0]
    assert got == 8, f"Count(Intersect): {got} != 8"
    got = query(coord, "i", "Count(Difference(Bitmap(frame=f, rowID=1),"
                            " Bitmap(frame=f, rowID=2)))")[0]
    assert got == 4, f"Count(Difference): {got} != 4"

    # Batched Counts: one PQL query, one pod collective for all three —
    # the dispatch counter pins that the fused path engaged (the values
    # alone would also pass via per-call fallback).
    before = srv.pod.dispatch_counts.get("count_exprs", 0)
    res = query(coord, "i",
                "Count(Bitmap(frame=f, rowID=1))"
                " Count(Bitmap(frame=f, rowID=2))"
                " Count(Intersect(Bitmap(frame=f, rowID=1),"
                " Bitmap(frame=f, rowID=2)))")
    assert res == [12, 8, 8], res
    assert srv.pod.dispatch_counts.get("count_exprs", 0) == before + 1

    # Bitmap materialization rides the podLocal host legs: bits from
    # worker-owned slices must appear.
    bits = query(coord, "i", "Bitmap(frame=f, rowID=3)")[0]["bits"]
    assert bits == [s * SLICE_WIDTH for s in range(4)], bits

    # TopN candidate phase (rank caches on every process) + exact-count
    # phase (pod collective).
    pairs = query(coord, "i", "TopN(frame=f, n=2)")
    got = [(p["id"], p["count"]) for p in pairs[0]]
    assert got == [(1, 12), (2, 8)], got
    pairs = query(coord, "i",
                  "TopN(Bitmap(frame=f, rowID=2), frame=f, ids=[1, 3])")
    got = [(p["id"], p["count"]) for p in pairs[0]]
    assert got == [(1, 8), (3, 4)], got

    # Filtered exact phase on the pod collective: per-slice threshold 2
    # drops row 3 (1 bit ∩ src per slice) but keeps row 1 (2 per slice).
    pairs = query(coord, "i", "TopN(Bitmap(frame=f, rowID=2), frame=f,"
                              " ids=[1, 3], threshold=2)")
    got = [(p["id"], p["count"]) for p in pairs[0]]
    assert got == [(1, 8)], got

    # Inverse views route by ROW slice inside the pod (a bit's standard
    # and inverse views can live on different processes) — the
    # per-view pinning in executor._pod_write_remote.
    http("POST", coord, "/index/i/frame/inv",
         b'{"options": {"inverseEnabled": true}}')
    for s in range(4):
        # row id s*W+7 → inverse slice s; column 2*W+1 → standard slice 2
        query(coord, "i", f"SetBit(frame=inv, rowID={s * SLICE_WIDTH + 7},"
                          f" columnID={2 * SLICE_WIDTH + 1})")
    bits = query(coord, "i",
                 f"Bitmap(frame=inv, columnID={2 * SLICE_WIDTH + 1})"
                 )[0]["bits"]
    assert bits == [s * SLICE_WIDTH + 7 for s in range(4)], bits

    # Bulk /import through the coordinator splits within the pod:
    # standard+time views go to the column-slice owner, inverse views
    # group by ROW slice with one leg per owning process
    # (handler._pod_import + podView legs).
    from pilosa_tpu.proto import internal_pb2 as pb
    http("POST", coord, "/index/i/frame/imp",
         b'{"options": {"inverseEnabled": true}}')
    rows_i = [s * SLICE_WIDTH + 3 for s in range(4)]   # 4 inverse slices
    cols_i = [1 * SLICE_WIDTH + 9] * 4                 # one standard slice
    body = pb.ImportRequest(
        Index="i", Frame="imp", Slice=1,
        RowIDs=rows_i, ColumnIDs=cols_i,
        Timestamps=[0] * 4).SerializeToString()
    http("POST", coord, "/import", body, "application/x-protobuf")
    bits = query(coord, "i",
                 f"Bitmap(frame=imp, columnID={cols_i[0]})")[0]["bits"]
    assert bits == rows_i, bits
    got = query(coord, "i",
                f"Count(Bitmap(frame=imp, rowID={rows_i[2]}))")[0]
    assert got == 1, got

    # Range over time views runs the podLocal host legs with view names.
    http("POST", coord, "/index/i/frame/tq",
         b'{"options": {"timeQuantum": "YMD"}}')
    for s in range(4):
        query(coord, "i", f"SetBit(frame=tq, rowID=1,"
                          f" columnID={s * SLICE_WIDTH},"
                          f' timestamp="2017-01-0{s + 1}T00:00")')
    got = query(coord, "i", 'Count(Range(rowID=1, frame=tq,'
                            ' start="2017-01-01T00:00",'
                            ' end="2017-01-03T00:00"))')[0]
    assert got == 2, got

    # Randomized parity: pod results must equal a pure host model.
    import random
    rng = random.Random(7)
    model = {1: set(), 2: set()}
    for _ in range(60):
        row = rng.choice((1, 2))
        col = rng.randrange(4 * SLICE_WIDTH)
        query(coord, "i", f"SetBit(frame=f, rowID={row}, columnID={col})")
        model[row].add(col)
    for s in range(4):
        for j in range(3):
            model[1].add(s * SLICE_WIDTH + j)
        for j in range(2):
            model[2].add(s * SLICE_WIDTH + j)
    got = query(coord, "i", "Count(Union(Bitmap(frame=f, rowID=1),"
                            " Bitmap(frame=f, rowID=2)))")[0]
    assert got == len(model[1] | model[2]), got
    got = query(coord, "i", "Count(Intersect(Bitmap(frame=f, rowID=1),"
                            " Bitmap(frame=f, rowID=2)))")[0]
    assert got == len(model[1] & model[2]), got
    bits = query(coord, "i", "Bitmap(frame=f, rowID=2)")[0]["bits"]
    assert bits == sorted(model[2]), (len(bits), len(model[2]))

    # Pod executions really did run: the coordinator's executor must not
    # have fallen back to the (coordinator-only) host path silently.
    assert srv.executor.device_fallbacks == 0, srv.executor.device_fallbacks

    if os.environ.get("POD_TEST_POISON") == "1":
        poison_phase(srv, coord, model)

    print("POD_TEST_OK", flush=True)
    srv.close()


def poison_phase(srv, coord, model) -> None:
    """Force a real partial-dispatch failure, then prove the poisoned
    pod still serves correct results via the host fan-out under
    concurrent load (the pod's workers stay HTTP-alive; only the
    collective path is off)."""
    import concurrent.futures

    from pilosa_tpu.parallel.pod import PodError

    # A bogus work item is delivered to every worker (their legs error)
    # and the coordinator's own leg raises — the genuine poisoning
    # transition in Pod._dispatch, not a flag poke.
    try:
        srv.pod._dispatch({"kind": "bogus", "index": "i",
                           "slices": [0, 1, 2, 3], "leaves": []})
        raise AssertionError("bogus dispatch must raise")
    except PodError:
        pass
    assert srv.pod._poisoned, "partial dispatch failure must poison"

    want_union = len(model[1] | model[2])
    want_r1, want_r2 = len(model[1]), len(model[2])

    def check(_):
        got = query(coord, "i",
                    "Count(Union(Bitmap(frame=f, rowID=1),"
                    " Bitmap(frame=f, rowID=2)))")[0]
        assert got == want_union, (got, want_union)
        bits = query(coord, "i", "Bitmap(frame=f, rowID=2)")[0]["bits"]
        assert bits == sorted(model[2]), len(bits)
        pairs = query(coord, "i", "TopN(frame=f, n=2)")[0]
        got = [(p["id"], p["count"]) for p in pairs]
        assert got == [(1, want_r1), (2, want_r2)], got
        return True

    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        assert all(ex.map(check, range(24)))
    # The device path really was refused and the host fan-out used.
    assert srv.executor.device_fallbacks > 0
    try:
        srv.pod._dispatch({"kind": "count_expr", "index": "i",
                           "expr": [], "leaves": [], "slices": [0]})
        raise AssertionError("poisoned pod must refuse collectives")
    except PodError:
        pass


if __name__ == "__main__":
    child_main(main)
