"""Calibrated device/host routing (parallel.costmodel).

Round 2's measured c4 showed the static slice threshold routing
128-slice Counts onto a device path ~4× slower than the host through
the tunnel. The cost model predicts per query from measured hardware
numbers; these tests pin the decision function on injected calibrations
for both hardware classes, and that the executor's veto actually routes
a query onto the host path under a tunnel-shaped calibration.
"""

import numpy as np

from pilosa_tpu.ops.packed import WORDS_PER_SLICE
from pilosa_tpu.parallel.costmodel import Calibration, CostModel


def block_bytes(rows: int, slices: int) -> int:
    return rows * slices * WORDS_PER_SLICE * 4


# Round-2 measured shapes: tunnel sync ~130 ms, host roaring ~1 GB/s.
TUNNEL = Calibration(sync_s=0.130, host_bps=1.0e9)
# Direct-attached chip: ~1 ms sync, same host.
DIRECT = Calibration(sync_s=0.001, host_bps=1.0e9)


class TestDecision:
    def test_tunnel_c4_routes_host(self):
        # BASELINE config 4: Count(Intersect) = 2 leaves × 128 slices
        # (~34 MB). Host ~33 ms vs device ≥130 ms — clear host win.
        m = CostModel(TUNNEL)
        assert not m.device_pays(block_bytes(2, 128))

    def test_tunnel_1gbit_rows_route_device(self):
        # The metric of record: 2 leaves × 1024 slices (~268 MB).
        # Host ~268 ms vs device ~131 ms — device wins even on tunnel.
        m = CostModel(TUNNEL)
        assert m.device_pays(block_bytes(2, 1024))

    def test_direct_attach_routes_device_at_c4(self):
        # Without the tunnel floor the same c4 shape belongs on device.
        m = CostModel(DIRECT)
        assert m.device_pays(block_bytes(2, 128))

    def test_cold_upload_flips_decision_on_tunnel(self):
        # TopN phase 2: 1000 candidates × 10 slices (~1.3 GB block).
        # Resident, the device wins (host ~1.3 s vs sync floor); cold,
        # the upload at a tunnel-rate 100 MB/s (~13 s) hands it to the
        # host.
        cal = Calibration(sync_s=0.130, host_bps=1.0e9, upload_bps=1.0e8)
        m = CostModel(cal)
        bytes_ = block_bytes(1000, 10)
        assert m.device_pays(bytes_, cold_bytes=0)
        assert not m.device_pays(bytes_, cold_bytes=bytes_)

    def test_cold_upload_cheap_on_direct_attach(self):
        # Direct-attached: 20 GB/s transfers make the same cold block a
        # device win again. pack_bps is pinned — this hypothetical rig
        # packs at memory speed; the shipped default is the measured
        # (much slower) CPU-rig rate and isn't under test here.
        cal = Calibration(sync_s=0.001, host_bps=1.0e9,
                          upload_bps=2.0e10, pack_bps=2.0e9)
        bytes_ = block_bytes(1000, 10)
        assert CostModel(cal).device_pays(bytes_, cold_bytes=bytes_)

    def test_margin_keeps_marginal_shapes_on_device(self):
        # Host must be a CLEAR win (margin 0.5): a shape where host
        # cost ≈ device cost stays on the device path.
        cal = Calibration(sync_s=0.010, host_bps=1.0e9)
        bytes_ = int(0.010 * 1.0e9)  # host cost == sync cost
        assert CostModel(cal, margin=0.5).device_pays(bytes_)
        assert not CostModel(cal, margin=1.5).device_pays(bytes_)


class TestExecutorVeto:
    def test_veto_routes_query_to_host(self, tmp_path):
        """With an injected tunnel calibration, a wide Count above the
        static slice floor must serve via the host path (no device
        dispatch), and still answer correctly."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu import SLICE_WIDTH

        holder = Holder(str(tmp_path))
        holder.open()
        idx = holder.create_index("i")
        frame = idx.create_frame("f")
        n_slices = 16
        cols = np.arange(n_slices, dtype=np.uint64) * np.uint64(
            SLICE_WIDTH)
        frame.import_bits(np.zeros(n_slices, dtype=np.uint64), cols)
        frame.import_bits(np.zeros(n_slices, dtype=np.uint64),
                          cols + np.uint64(1))

        ex = Executor(holder, host="h", mesh_min_slices=1)
        # Tunnel-shaped hardware: host clearly wins at 16 slices.
        # (conftest disables the model by default for determinism —
        # re-enable it here with an injected calibration.)
        ex._cost_model_enabled = True
        ex.cost_model = CostModel(TUNNEL)
        try:
            got = ex.execute(
                "i", 'Count(Bitmap(frame="f", rowID=0))',
                list(range(n_slices)))
            assert got == [2 * n_slices]
            assert ex.cost_vetoes > 0, "tunnel calibration must veto"
            assert ex.device_fallbacks == 0  # a veto is not a failure

            # Same query with the model disabled takes the device path.
            ex2 = Executor(holder, host="h", mesh_min_slices=1)
            ex2._cost_model_enabled = False
            got = ex2.execute(
                "i", 'Count(Bitmap(frame="f", rowID=0))',
                list(range(n_slices)))
            assert got == [2 * n_slices]
            assert ex2.cost_vetoes == 0
            assert ex2._mesh is not None, "device path must engage"
            ex2.close()
        finally:
            ex.close()
            holder.close()


class TestFeedbackLoop:
    def test_injected_drift_reconverges_without_restart(self):
        """A model calibrated with a wildly wrong host rate initially
        routes to the host; feeding it real observations (host 100x
        slower than predicted) recalibrates the host scale in-process
        until the device wins the prediction again — no restart."""
        from pilosa_tpu.parallel.costmodel import (
            Calibration, CostModel, DRIFT_MIN_SAMPLES)
        # Bogus probe: host believed to run at 1 TB/s (off ~100x);
        # device pays 10 ms sync. For a 100 MB query the model predicts
        # host 0.1 ms vs device >= 10 ms -> routes host.
        cal = Calibration(sync_s=0.010, host_bps=1e12, upload_bps=1e9)
        m = CostModel(cal, margin=0.5)
        nbytes = 100 << 20
        assert not m.device_pays(nbytes)  # mis-routed to host
        # Reality: the host does ~10 GB/s -> each query takes ~10 ms.
        recals = 0
        for _ in range(5 * DRIFT_MIN_SAMPLES):
            if m.device_pays(nbytes):
                break
            pred = m.predict("host", nbytes)
            actual = nbytes / 1e10
            m.record("host", pred, actual)
        else:
            raise AssertionError("model never re-converged")
        assert m.recalibrations >= 1
        # After convergence the host cost is priced ~100x higher and
        # the device serves the query.
        assert m.device_pays(nbytes)

    def test_scales_clamped_and_persisted(self, tmp_path, monkeypatch):
        import json
        from pilosa_tpu.parallel import costmodel as cm
        monkeypatch.setenv("PILOSA_TPU_CACHE", str(tmp_path))
        cal = cm.Calibration(sync_s=0.001, host_bps=1e9)
        m = cm.CostModel(cal, persist_key="testnode-cpu")
        for _ in range(cm.DRIFT_MIN_SAMPLES):
            m.record("host", 0.001, 1000.0)  # drift 1e6 -> clamped
        assert cal.host_scale <= cm._SCALE_CLAMP
        data = json.loads(
            (tmp_path / "costcal-testnode-cpu.json").read_text())
        assert data["host_scale"] == cal.host_scale

    def test_persisted_calibration_reloads(self, tmp_path, monkeypatch):
        from pilosa_tpu.parallel import costmodel as cm
        monkeypatch.setenv("PILOSA_TPU_CACHE", str(tmp_path))
        cal = cm.Calibration(sync_s=0.123, host_bps=5e8,
                             upload_bps=2e9, host_scale=3.0)
        cm._persist_calibration("n-p", cal)
        got = cm._load_calibration("n-p")
        assert got == cal


class TestExecutorFeedbackWiring:
    def test_vetoed_count_records_host_leg(self, tmp_path):
        """The veto stamps a per-query note (set on a _map_reduce pool
        worker) and the query site must record the host leg — a
        threading.local here silently dropped every record (round-4
        review finding)."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu import SLICE_WIDTH

        h = Holder(str(tmp_path / "d"))
        h.open()
        try:
            idx = h.create_index_if_not_exists("i")
            f = idx.create_frame_if_not_exists("f")
            for col in (1, SLICE_WIDTH + 2, 2 * SLICE_WIDTH + 3):
                f.set_bit("standard", 1, col)
                f.set_bit("standard", 2, col)
            ex = Executor(h, host="local", use_mesh=True,
                          mesh_min_slices=1)

            recorded = []

            class VetoModel:
                margin = 0.5

                def device_pays(self, total_bytes, cold_bytes=0,
                            streaming=False):
                    return False

                def predict(self, leg, total_bytes, cold_bytes=0):
                    return 0.001

                def record(self, leg, pred, actual):
                    recorded.append((leg, pred, actual))

            ex.cost_model = VetoModel()
            ex._cost_model_enabled = True
            got = ex.execute(
                "i", 'Count(Intersect(Bitmap(rowID=1, frame=f),'
                     ' Bitmap(rowID=2, frame=f)))')
            assert got == [3]
            legs = [r[0] for r in recorded]
            assert "host" in legs, recorded
        finally:
            h.close()


class TestStreamingLeg:
    def test_packing_term_priced_into_streaming_prediction(self):
        """The streaming device prediction includes the host-side pack
        cost (cold bytes / pack_bps) — round 4 excluded streaming legs
        from drift recording precisely because this term was
        unpriced."""
        from pilosa_tpu.parallel.costmodel import Calibration
        cal = Calibration(sync_s=0.001, host_bps=1e9, upload_bps=1e9,
                          pack_bps=2e9)
        nbytes = 64 << 20
        base = cal.device_cost(nbytes, cold_bytes=0)
        cold = cal.device_cost(nbytes, cold_bytes=nbytes)
        # The cold form must include upload AND pack terms.
        want_extra = nbytes / 1e9 + nbytes / 2e9
        assert abs((cold - base) - want_extra) < 1e-6

    def test_streaming_mispricing_reconverges_own_scale(self):
        """An injected streaming-leg mispricing re-converges via
        stream_scale — and the drift snapshot shows the streaming
        samples (VERDICT r4 item 6 'done' criteria)."""
        from pilosa_tpu.parallel.costmodel import (
            Calibration, CostModel, DRIFT_MIN_SAMPLES)
        cal = Calibration(sync_s=0.001, host_bps=1e9, upload_bps=100e9,
                          pack_bps=200e9)  # pack believed ~free: wrong
        m = CostModel(cal, margin=0.5)
        nbytes = 64 << 20
        # Reality: packing runs at 1 GB/s on this host — ~30x the
        # predicted streaming cost (fast direct-attach upload, so the
        # pack term dominates).
        for _ in range(DRIFT_MIN_SAMPLES):
            pred = m.predict("device_stream", nbytes, cold_bytes=nbytes)
            actual = 0.001 + nbytes / 100e9 + nbytes / 1e9
            m.record("device_stream", pred, actual)
        snap = m.drift_snapshot()
        assert m.recalibrations >= 1
        assert cal.stream_scale > 1.5  # corrected upward
        assert cal.device_scale == 1.0  # resident legs untouched
        # Post-correction predictions sit within the drift bound.
        pred = m.predict("device_stream", nbytes, cold_bytes=nbytes)
        actual = 0.001 + nbytes / 100e9 + nbytes / 1e9
        assert 0.4 <= actual / pred <= 2.5
        assert "device_stream" in snap

    def test_snapshot_reports_stream_samples(self):
        from pilosa_tpu.parallel.costmodel import Calibration, CostModel
        m = CostModel(Calibration(sync_s=0.001, host_bps=1e9), margin=0.5)
        m.record("device_stream", 0.010, 0.012)
        snap = m.drift_snapshot()
        assert snap["device_stream"]["n"] == 1
        assert "streamScale" in snap
