"""Child process for the real-worker-death pod test (test_pod.py).

An 8-process CPU pod (1 virtual device each). The coordinator builds
data in slices 0-6 (worker 7 owns no data slice), proves the
device-collective path works, then signals the LAUNCHER to SIGKILL
worker 7 and waits for the sentinel file. The next collective stalls —
workers 0-6 enter it, 7 never joins — and the coordinator must:

1. time the stalled collective out via PILOSA_TPU_POD_TIMEOUT (set low
   by the launcher; the round-3 gap was that this path had never been
   induced by an actual death),
2. poison the device path, and
3. keep serving correct results under concurrent load through the
   podLocal host fan-out (whose legs only touch live owners).

Style mirror: reference whole-process cluster tests
(server/server_test.go:375-496).

Usage: python pod_kill_child.py <proc_id> <data_dir>
"""

import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)

from podenv import child_main, http, query  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402
from pilosa_tpu.server.server import Server  # noqa: E402


def main() -> None:
    proc_id = int(sys.argv[1])
    data_dir = sys.argv[2]
    host = os.environ["PILOSA_TPU_POD_PEERS"].split(",")[proc_id]

    srv = Server(data_dir, host=host, anti_entropy_interval=0,
                 polling_interval=0)
    srv.open()
    print(f"pod process {proc_id} serving on {srv.host}", flush=True)

    if proc_id != 0:
        while True:  # worker: serve pod legs until killed
            time.sleep(0.5)

    coord = srv.host
    http("POST", coord, "/index/i", b"{}")
    http("POST", coord, "/index/i/frame/f", b"{}")

    # Bits in slices 0..6 only: worker 7 owns slice 7 (empty), so the
    # post-death host fan-out never needs the dead process's data —
    # but the COLLECTIVE still spans all 8 processes and must stall.
    n_slices = 7
    for s in range(n_slices):
        for j in range(3):
            query(coord, "i", f"SetBit(frame=f, rowID=1,"
                              f" columnID={s * SLICE_WIDTH + j})")
        for j in range(2):
            query(coord, "i", f"SetBit(frame=f, rowID=2,"
                              f" columnID={s * SLICE_WIDTH + j})")

    # Collective path alive pre-kill (8-way psum over gloo). The warm
    # collective compiles 8 programs on ONE time-shared core, so the
    # tight kill-phase timeout would false-trip here: warm with a
    # generous bound, then arm the configured (low) timeout for the
    # death phase — the mechanism under test is the same.
    tight = srv.pod.timeout
    srv.pod.timeout = 240.0
    got = query(coord, "i", "Count(Bitmap(frame=f, rowID=1))")[0]
    assert got == 3 * n_slices, got
    assert srv.pod.dispatch_counts.get("count_expr", 0) >= 1
    srv.pod.timeout = tight

    # Hand control to the launcher: it SIGKILLs worker 7, then writes
    # the sentinel file.
    sentinel = os.environ["POD_KILL_SENTINEL"]
    print("READY_FOR_KILL", flush=True)
    deadline = time.time() + 60
    while not os.path.exists(sentinel):
        if time.time() > deadline:
            raise RuntimeError("launcher never wrote the kill sentinel")
        time.sleep(0.1)

    # The next collective must STALL (workers 0-6 enter, 7 never does)
    # and the coordinator must exit it via PILOSA_TPU_POD_TIMEOUT.
    from pilosa_tpu.parallel.pod import PodError
    t0 = time.time()
    try:
        srv.pod.count_expr("i", ("leaf", 0),
                           [("f", "standard", 1)],
                           list(range(n_slices + 1)))
        raise AssertionError("collective with a dead worker must fail")
    except PodError as e:
        elapsed = time.time() - t0
        budget = float(os.environ["PILOSA_TPU_POD_TIMEOUT"])
        # Reachability pre-checks may catch the death first (fast); a
        # stall must be cut at ~the timeout, not hang forever.
        assert elapsed < budget + 30, (elapsed, str(e))
    assert srv.pod._poisoned, "dead worker must poison the pod"

    # Poisoned pod + dead worker: correct results via the host fan-out,
    # under concurrent load (legs only touch live owners).
    import concurrent.futures

    def check(_):
        got = query(coord, "i", "Count(Bitmap(frame=f, rowID=1))")[0]
        assert got == 3 * n_slices, got
        got = query(coord, "i",
                    "Count(Intersect(Bitmap(frame=f, rowID=1),"
                    " Bitmap(frame=f, rowID=2)))")[0]
        assert got == 2 * n_slices, got
        pairs = query(coord, "i", "TopN(frame=f, n=2)")[0]
        tops = [(p["id"], p["count"]) for p in pairs]
        assert tops == [(1, 3 * n_slices), (2, 2 * n_slices)], tops
        return True

    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        assert all(ex.map(check, range(24)))

    # And a further collective attempt fails FAST (poisoned guard),
    # not by re-stalling for another timeout.
    t0 = time.time()
    try:
        srv.pod._dispatch({"kind": "count_expr", "index": "i",
                           "expr": ["leaf", 0],
                           "leaves": [["f", "standard", 1]],
                           "slices": [0]})
        raise AssertionError("poisoned dispatch must raise")
    except PodError:
        assert time.time() - t0 < 5
    print("POD_KILL_TEST_OK", flush=True)


child_main(main)
