"""CI sweeps for the observability surface: every emitted metric name
must follow ``pilosa_<subsystem>_<noun>_<unit>``, and every ``/debug/*``
+ ``/metrics`` route registered in the handler must appear in the
README route documentation — new endpoints cannot ship undocumented."""

import os
import re

from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.server.handler import Handler

_README = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "README.md")


class TestMetricNamingSweep:
    def test_all_registered_names_follow_convention(self):
        fams = obs_metrics.default_registry().families()
        assert fams, "registry empty at import — declarations moved?"
        for name, fam in fams.items():
            assert obs_metrics.NAME_RE.match(name), (
                f"metric {name} outside pilosa_<subsystem>_<noun>_"
                f"<unit>")
            if fam.type == "counter":
                assert name.endswith("_total"), (
                    f"counter {name} must end in _total")
            else:
                assert not name.endswith("_total"), (
                    f"non-counter {name} must not claim _total")

    def test_rendered_sample_names_follow_convention(self):
        """The rendered exposition can only emit family names plus the
        histogram suffixes — validate the actual output lines too."""
        sample_re = re.compile(
            r"^(pilosa(?:_[a-z][a-z0-9]*){3,}"
            r"(?:_bucket|_sum|_count)?)[ {]")
        for line in obs_metrics.default_registry().render().splitlines():
            if not line or line.startswith("#"):
                continue
            assert sample_re.match(line), f"bad sample line: {line!r}"

    def test_stats_bridge_names_follow_convention(self):
        """Legacy stats names that flow through the bridge must come
        out convention-clean for every name style in the codebase."""
        reg = obs_metrics.Registry()
        bridge = obs_metrics.RegistryStatsClient(reg)
        for legacy in ("setN", "clearN", "indexN", "slowQueries",
                       "queriesRejected", "deviceFallback",
                       "snapshotDurationNs", "slowQueryNs"):
            bridge.count(legacy)
            bridge.gauge(legacy, 1.0)
            bridge.timing(legacy, 1.0)
        for name in reg.families():
            assert obs_metrics.NAME_RE.match(name), name


class TestRouteTableDocumented:
    def test_debug_and_metrics_routes_in_readme(self):
        handler = Handler(None, None)
        with open(_README) as f:
            readme = f.read()
        swept = []
        for _method, _regex, _fn, _lane, pattern in handler._routes:
            if pattern == "/metrics" or pattern.startswith("/debug/"):
                swept.append(pattern)
                # Variable segments differ in name between code and
                # docs ({qid} vs {id}); the static prefix must appear
                # verbatim in the README.
                prefix = pattern.split("{")[0]
                assert prefix in readme, (
                    f"route {pattern} is registered in handler.py but"
                    f" its prefix {prefix!r} is not documented in"
                    f" README.md")
        # The sweep itself must be seeing the observability routes.
        assert "/metrics" in swept
        assert any(p.startswith("/debug/traces") for p in swept)
        assert "/debug/queries/slow" in swept
