"""CI sweeps for the observability surface: every emitted metric name
must follow ``pilosa_<subsystem>_<noun>_<unit>``, and every ``/debug/*``
+ ``/metrics`` route registered in the handler must appear in the
README route documentation — new endpoints cannot ship undocumented."""

import os
import re

from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.server.handler import Handler

_README = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "README.md")


class TestMetricNamingSweep:
    def test_all_registered_names_follow_convention(self):
        fams = obs_metrics.default_registry().families()
        assert fams, "registry empty at import — declarations moved?"
        for name, fam in fams.items():
            assert obs_metrics.NAME_RE.match(name), (
                f"metric {name} outside pilosa_<subsystem>_<noun>_"
                f"<unit>")
            if fam.type == "counter":
                assert name.endswith("_total"), (
                    f"counter {name} must end in _total")
            else:
                assert not name.endswith("_total"), (
                    f"non-counter {name} must not claim _total")

    def test_rendered_sample_names_follow_convention(self):
        """The rendered exposition can only emit family names plus the
        histogram suffixes — validate the actual output lines too.
        (``pilosa_build_info`` rides the OpenMetrics *info*-gauge
        exception, mirrored from obs_metrics.NAME_RE.)"""
        sample_re = re.compile(
            r"^(pilosa(?:_[a-z][a-z0-9]*){3,}"
            r"(?:_bucket|_sum|_count)?"
            r"|pilosa(?:_[a-z][a-z0-9]*)+_info)[ {]")
        for line in obs_metrics.default_registry().render().splitlines():
            if not line or line.startswith("#"):
                continue
            assert sample_re.match(line), f"bad sample line: {line!r}"

    def test_stats_bridge_names_follow_convention(self):
        """Legacy stats names that flow through the bridge must come
        out convention-clean for every name style in the codebase."""
        reg = obs_metrics.Registry()
        bridge = obs_metrics.RegistryStatsClient(reg)
        for legacy in ("setN", "clearN", "indexN", "slowQueries",
                       "queriesRejected", "deviceFallback",
                       "snapshotDurationNs", "slowQueryNs"):
            bridge.count(legacy)
            bridge.gauge(legacy, 1.0)
            bridge.timing(legacy, 1.0)
        for name in reg.families():
            assert obs_metrics.NAME_RE.match(name), name


class TestTenantFamiliesSwept:
    """ISSUE 14: the pilosa_tenant_* chargeback families must exist,
    follow the naming convention (the sweep above already enforces
    that for everything registered), carry a ``tenant`` label, and
    ride a bounded label set (the PR-10 overflow bucket) — a
    tenant-per-customer deployment must not blow up the exposition."""

    _FAMILIES = (
        "pilosa_tenant_query_duration_seconds",
        "pilosa_tenant_query_requests_total",
        "pilosa_tenant_cost_units_total",
        "pilosa_tenant_admission_rejections_total",
        "pilosa_tenant_cost_kills_total",
        "pilosa_tenant_inflight_queries",
        "pilosa_tenant_penalty_score",
        "pilosa_tenant_cache_bytes",
        "pilosa_tenant_slo_burn_rate_ratio",
    )

    def test_families_registered_with_tenant_label(self):
        fams = obs_metrics.default_registry().families()
        for name in self._FAMILIES:
            assert name in fams, f"tenant family {name} not registered"
            fam = fams[name]
            assert "tenant" in fam.labelnames, (
                f"{name} must carry a tenant label,"
                f" has {fam.labelnames}")
            assert fam.max_label_sets <= 512, (
                f"{name} must ride an explicit bounded label set")

    def test_overflow_bucket_engages(self):
        """Past the cap, new tenants collapse into _overflow_ instead
        of growing the family unboundedly."""
        fam = obs_metrics.TENANT_KILLS
        for i in range(fam.max_label_sets + 8):
            fam.labels(f"naming-sweep-tenant-{i}").inc()
        labelsets = [labels for labels, _ in fam._label_dicts()]
        assert len(labelsets) <= fam.max_label_sets + 1
        assert any(obs_metrics._OVERFLOW_LABEL in ls.values()
                   for ls in labelsets)


class TestRouteTableDocumented:
    def test_debug_and_metrics_routes_in_readme(self):
        handler = Handler(None, None)
        with open(_README) as f:
            readme = f.read()
        swept = []
        for _method, _regex, _fn, _lane, pattern in handler._routes:
            if pattern == "/health" or pattern.startswith("/metrics") \
                    or pattern.startswith("/debug/"):
                swept.append(pattern)
                # Variable segments differ in name between code and
                # docs ({qid} vs {id}); the static prefix must appear
                # verbatim in the README.
                prefix = pattern.split("{")[0]
                assert prefix in readme, (
                    f"route {pattern} is registered in handler.py but"
                    f" its prefix {prefix!r} is not documented in"
                    f" README.md")
        # The sweep itself must be seeing the observability routes.
        assert "/metrics" in swept
        assert any(p.startswith("/debug/traces") for p in swept)
        assert "/debug/queries/slow" in swept
        assert "/debug/pprof/flame" in swept
        assert "/health" in swept
        # Fault subsystem: the failpoint admin endpoint must be both
        # registered and documented.
        assert "/debug/failpoints" in swept
        # Fleet observability (ISSUE 13): the federation, history,
        # sentinel, and trace-summary routes are registered AND
        # documented.
        assert "/metrics/cluster" in swept
        assert "/debug/metrics/history" in swept
        assert "/debug/cluster" in swept
        assert "/debug/sentinel" in swept
        assert "/debug/traces/summary" in swept

    def test_fleet_observability_metrics_registered(self):
        """ISSUE 13: the metric-history / federation / sentinel
        families exist (and so passed the naming gate at import), the
        sentinel findings counter carries the promised labels, and the
        tail sampler's keep-reason catalogue grew ``anomaly``."""
        fams = obs_metrics.default_registry().families()
        for name in ("pilosa_history_samples_total",
                     "pilosa_history_series_live",
                     "pilosa_history_series_dropped_total",
                     "pilosa_history_disk_records_total",
                     "pilosa_federation_scrapes_total",
                     "pilosa_sentinel_findings_total",
                     "pilosa_sentinel_findings_active",
                     "pilosa_sentinel_checks_total"):
            assert name in fams, name
        assert fams["pilosa_sentinel_findings_total"].labelnames == (
            "metric", "direction")
        assert fams["pilosa_sentinel_findings_active"].type == "gauge"
        assert fams["pilosa_federation_scrapes_total"].labelnames == (
            "peer", "outcome")
        from pilosa_tpu.obs.sampler import REASONS
        assert "anomaly" in REASONS
        # The summary route must precede the {qid} wildcard or the
        # wildcard swallows it.
        handler = Handler(None, None)
        patterns = [p for _m, _r, _f, _l, p in handler._routes]
        assert patterns.index("/debug/traces/summary") \
            < patterns.index("/debug/traces/{qid}")

    def test_roaring_container_metrics_registered(self):
        """The run-container observability families (docs/STORAGE.md):
        per-kind live-container and resident-byte gauges, and the op
        counter whose kind label grew the run operand kinds."""
        fams = obs_metrics.default_registry().families()
        for name in ("pilosa_roaring_containers_live",
                     "pilosa_roaring_container_bytes",
                     "pilosa_roaring_container_ops_total"):
            assert name in fams, name
        for name in ("pilosa_roaring_containers_live",
                     "pilosa_roaring_container_bytes"):
            assert fams[name].type != "counter", name
        from pilosa_tpu.storage import roaring
        assert set(roaring.OP_KINDS) >= {"run_run", "run_array",
                                         "run_bitmap"}

    def test_resize_metrics_and_routes_registered(self):
        """ISSUE 12: the elastic-resize metric families exist (and so
        passed the naming gate at import — the state gauge carries the
        cluster_ subsystem prefix the convention requires), the
        watchdog grew the resize_stall cause, the failpoint registry
        grew the resize.stream site, and the control/debug routes are
        registered."""
        fams = obs_metrics.default_registry().families()
        for name in ("pilosa_cluster_resize_state",
                     "pilosa_resize_slices_moved_total",
                     "pilosa_resize_stream_bytes_total",
                     "pilosa_cluster_resize_double_reads_total"):
            assert name in fams, name
        assert fams["pilosa_cluster_resize_state"].type == "gauge"
        assert fams["pilosa_resize_slices_moved_total"].type \
            == "counter"
        from pilosa_tpu.obs.watchdog import CAUSES
        assert "resize_stall" in CAUSES
        from pilosa_tpu.fault.failpoints import SITES
        assert "resize.stream" in SITES
        handler = Handler(None, None)
        patterns = {p for _m, _r, _f, _l, p in handler._routes}
        assert "/debug/topology" in patterns
        assert "/cluster/resize" in patterns
        assert "/fragment/import" in patterns

    def test_observability_pr_metrics_registered(self):
        """The tail-sampling / blackbox / watchdog metric families
        promised by docs/OBSERVABILITY.md exist in the default
        registry (and so passed the naming gate at import), and the
        build-info gauge uses the sanctioned _info exception."""
        fams = obs_metrics.default_registry().families()
        for name in ("pilosa_trace_kept_total",
                     "pilosa_trace_disk_records_total",
                     "pilosa_metrics_label_overflow_total",
                     "pilosa_watchdog_trips_total",
                     "pilosa_blackbox_snapshots_total",
                     "pilosa_blackbox_dumps_total",
                     "pilosa_build_info"):
            assert name in fams, name
        assert fams["pilosa_build_info"].type == "gauge"
        assert fams["pilosa_build_info"].labelnames == (
            "version", "python", "jax", "backend")
        assert fams["pilosa_trace_kept_total"].labelnames == ("reason",)
        assert fams["pilosa_watchdog_trips_total"].labelnames == (
            "cause",)

    def test_planner_metrics_registered(self):
        """ISSUE 18: the pilosa_planner_* families behind the planner
        observability plane exist in the default registry (and so
        passed the naming gate at import) with the documented label
        sets."""
        fams = obs_metrics.default_registry().families()
        for name in ("pilosa_planner_decisions_total",
                     "pilosa_planner_misestimation_ratio",
                     "pilosa_planner_subresult_cache_events_total",
                     "pilosa_planner_plan_seconds"):
            assert name in fams, name
        assert fams["pilosa_planner_decisions_total"].labelnames == (
            "outcome",)
        assert fams[
            "pilosa_planner_subresult_cache_events_total"
        ].labelnames == ("event",)
        assert fams["pilosa_planner_misestimation_ratio"].type == \
            "histogram"
        assert fams["pilosa_planner_plan_seconds"].type == "histogram"

    def test_planner_debug_route_registered(self):
        """GET /debug/plans is wired (the README sweep above enforces
        documentation)."""
        handler = Handler(None, None)
        assert any(pattern == "/debug/plans"
                   for _m, _r, _f, _l, pattern in handler._routes)

    def test_capture_routes_metrics_and_config_swept(self):
        """ISSUE 19: the workload-capture surface — both /debug/capture
        routes are registered (the README sweep above enforces their
        documentation), the pilosa_capture_* families exist with the
        documented labels (and so passed the naming gate at import),
        and every [capture] config key round-trips through to_toml."""
        handler = Handler(None, None)
        patterns = {p for _m, _r, _f, _l, p in handler._routes}
        assert "/debug/capture" in patterns
        assert "/debug/capture/records" in patterns
        fams = obs_metrics.default_registry().families()
        for name in ("pilosa_capture_records_total",
                     "pilosa_capture_dropped_total",
                     "pilosa_capture_bytes_total"):
            assert name in fams, name
            assert fams[name].type == "counter", name
        assert fams["pilosa_capture_records_total"].labelnames == (
            "kind",)
        assert fams["pilosa_capture_dropped_total"].labelnames == (
            "reason",)
        from pilosa_tpu.utils.config import Config
        toml = Config().to_toml()
        assert "[capture]" in toml
        for key in ("mode", "sample-n", "segment-bytes", "segments",
                    "redact"):
            assert f"\n{key} = " in toml.split("[capture]")[1], key

    def test_backup_routes_metrics_and_config_swept(self):
        """ISSUE 20: the disaster-recovery surface — the /backup
        control route and /debug/backup are registered and documented
        in the README, the pilosa_backup_* families exist with the
        documented labels (and so passed the naming gate at import),
        the watchdog grew the backup_stall cause, the failpoint
        registry grew the backup.push / restore.fetch sites, the
        tail sampler knows the ``backup`` keep-reason, and every
        [backup] config key round-trips through to_toml."""
        handler = Handler(None, None)
        patterns = {p for _m, _r, _f, _l, p in handler._routes}
        assert "/backup" in patterns
        assert "/debug/backup" in patterns
        with open(_README) as f:
            readme = f.read()
        for surface in ("/backup", "/debug/backup",
                        "--to-timestamp", "--sweep-orphans",
                        "check --deep --archive"):
            assert surface in readme, (
                f"backup surface {surface!r} undocumented in README")
        fams = obs_metrics.default_registry().families()
        for name in ("pilosa_backup_objects_total",
                     "pilosa_backup_bytes_total",
                     "pilosa_backup_fragments_total",
                     "pilosa_backup_wal_records_total",
                     "pilosa_backup_wal_segments_total",
                     "pilosa_backup_errors_total"):
            assert name in fams, name
            assert fams[name].type == "counter", name
        assert fams["pilosa_backup_state_info"].type == "gauge"
        assert fams["pilosa_backup_state_info"].labelnames == (
            "phase",)
        assert fams["pilosa_backup_objects_total"].labelnames == (
            "outcome",)
        assert fams["pilosa_backup_bytes_total"].labelnames == (
            "direction",)
        from pilosa_tpu.obs.watchdog import CAUSES
        assert "backup_stall" in CAUSES
        from pilosa_tpu.fault.failpoints import SITES
        assert "backup.push" in SITES
        assert "restore.fetch" in SITES
        from pilosa_tpu.obs.sampler import REASONS
        assert "backup" in REASONS
        from pilosa_tpu.utils.config import Config
        toml = Config().to_toml()
        assert "[backup]" in toml
        for key in ("archive", "wal-interval", "keep-fulls"):
            assert f"\n{key} = " in toml.split("[backup]")[1], key
        assert "backup-stall" in toml.split("[watchdog]")[1]

    def test_fault_metrics_registered(self):
        """The fault-layer metric families promised by
        docs/FAULT_TOLERANCE.md exist in the default registry (and so
        passed the naming-convention gate at import)."""
        fams = obs_metrics.default_registry().families()
        for name in ("pilosa_cluster_peer_health",
                     "pilosa_fault_breaker_state",
                     "pilosa_fault_breaker_transitions_total",
                     "pilosa_fault_failpoint_triggers_total",
                     "pilosa_cluster_failover_slices_total",
                     "pilosa_cluster_hedged_requests_total",
                     "pilosa_query_partial_results_total"):
            assert name in fams, name


class TestLabelCardinalityGuard:
    def test_overflow_bucket_caps_label_sets(self):
        """Per-family label-set cap (per-peer families grow with
        cluster size): past the cap, NEW label sets collapse into ONE
        ``_overflow_`` bucket and the overflow counter ticks — the
        registry's memory/scrape size stays bounded however many peers
        churn through."""
        reg = obs_metrics.Registry()
        fam = reg.histogram("pilosa_test_peer_rpc_seconds",
                            labels=("peer",), buckets=(0.1, 1.0),
                            max_label_sets=4)
        for i in range(4):
            fam.labels(f"peer-{i}").observe(0.05)
        overflow_before = obs_metrics.LABEL_OVERFLOW.labels(
            "pilosa_test_peer_rpc_seconds").value
        # Past the cap: every new peer lands in the shared bucket.
        for i in range(4, 20):
            fam.labels(f"peer-{i}").observe(0.05)
        with fam._mu:
            children = dict(fam._children)
        assert len(children) == 5  # 4 real + the overflow bucket
        assert ("_overflow_",) in children
        _counts, _sum, n = children[("_overflow_",)].snapshot()
        assert n == 16
        assert obs_metrics.LABEL_OVERFLOW.labels(
            "pilosa_test_peer_rpc_seconds").value \
            == overflow_before + 16
        # Pre-cap children keep resolving to their own series.
        fam.labels("peer-0").observe(0.05)
        _counts, _sum, n0 = children[("peer-0",)].snapshot()
        assert n0 == 2
        # The rendered exposition carries the overflow bucket as a
        # plain label value — scrapers need no special casing.
        assert '_overflow_' in reg.render()

    def test_overflow_counter_never_recurses(self):
        """The overflow counter itself is labeled by family; it must
        be exempt from its own cap (a recursion there would deadlock
        registration)."""
        for i in range(obs_metrics.DEFAULT_MAX_LABEL_SETS + 8):
            obs_metrics.LABEL_OVERFLOW.labels(
                f"pilosa_test_family_{i}_total")
        # Reaching here without RecursionError is the assertion; spot
        # check one child exists under its own name.
        assert obs_metrics.LABEL_OVERFLOW.labels(
            "pilosa_test_family_0_total") is not None


# One OpenMetrics 1.0 metric line, optionally with an exemplar:
#   name{labels} value [# {exemplar-labels} value timestamp]
_OM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r" (NaN|[-+]?(?:[0-9.eE+-]+|Inf))"
    r"(?: # \{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\}"
    r" ([-+]?[0-9.eE+-]+)(?: ([0-9.]+))?)?$")


class TestOpenMetricsExemplars:
    def test_exemplar_output_parses_as_openmetrics(self):
        """The OpenMetrics rendering must be structurally valid:
        counter families declared under the _total-stripped name,
        exemplars only on bucket/counter samples, terminated by
        # EOF — and the exemplar we recorded must surface with its
        trace id."""
        reg = obs_metrics.Registry()
        c = reg.counter("pilosa_test_events_total")
        c.inc(3)
        h = reg.histogram("pilosa_test_latency_seconds",
                          buckets=(0.1, 1.0))
        h.observe(0.05, exemplar={"trace_id": "abc123"})
        h.observe(5.0)
        text = reg.render(openmetrics=True)
        lines = text.splitlines()
        assert lines[-1] == "# EOF"
        # Counter TYPE under the stripped name; sample keeps _total.
        assert "# TYPE pilosa_test_events counter" in lines
        assert any(ln.startswith("pilosa_test_events_total 3")
                   for ln in lines)
        saw_exemplar = False
        for ln in lines:
            if not ln or ln.startswith("#"):
                continue
            m = _OM_LINE.match(ln)
            assert m, f"unparseable OpenMetrics line: {ln!r}"
            if m.group(4):  # exemplar present
                assert "_bucket" in m.group(1), (
                    "exemplar on a non-bucket sample")
                if 'trace_id="abc123"' in m.group(4):
                    saw_exemplar = True
        assert saw_exemplar, text
        # The 0.0.4 rendering of the same registry must NOT carry
        # exemplars (old scrapers would choke).
        assert " # {" not in reg.render()

    def test_default_registry_openmetrics_renders_clean(self):
        text = obs_metrics.default_registry().render(openmetrics=True)
        assert text.rstrip().endswith("# EOF")
        for ln in text.splitlines():
            if not ln or ln.startswith("#"):
                continue
            assert _OM_LINE.match(ln), f"bad OpenMetrics line: {ln!r}"
