"""Distributed BSI: a REAL 2-node gossip cluster (replicas=1, so
slices — and therefore field bit-planes — split across the nodes) must
answer Range and Sum/Min/Max with per-slice partial aggregates merged
across nodes, matching a dict-of-ints model from EITHER node. Covers
the ImportValue owner fan-out, the Range/aggregate remote legs and
their ValCount wire form, and SetFieldValue write forwarding."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402


def _post(host: str, path: str, body: bytes) -> bytes:
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=30).read()


def _query(host: str, body: str):
    return json.loads(_post(host, "/index/bc/query",
                            body.encode()))["results"]


def test_two_node_range_and_aggregate_merge(tmp_path):
    pa, pb = free_port(), free_port()
    ga, gb = free_port(), free_port()
    hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    procs = []
    logs = []

    def spawn(name, port, internal, seed=""):
        d = tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        log = open(tmp_path / f"{name}.log", "a")
        logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--cluster.type", "gossip",
                "--cluster.hosts", hosts,
                "--cluster.replicas", "1",
                "--cluster.internal-port", str(internal),
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        procs.append(p)
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    try:
        host_a = spawn("a", pa, ga)
        host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
        _post(host_a, "/index/bc", b"{}")
        _post(host_a, "/index/bc/frame/f", b"{}")
        _post(host_a, "/index/bc/frame/f/field/v",
              json.dumps({"min": -100, "max": 1000}).encode())

        from pilosa_tpu.cluster.client import Client
        client = Client(host_a)

        # Values spanning 4 slices: with replicas=1 the owner fan-out
        # necessarily lands planes on BOTH nodes.
        rng = np.random.default_rng(17)
        n_cols = 4 * SLICE_WIDTH
        cols = rng.choice(n_cols, size=400, replace=False) \
            .astype(np.uint64)
        vals = rng.integers(-100, 1001, len(cols)).astype(np.int64)
        client.import_field_values("bc", "f", "v", cols, vals)
        model = dict(zip(cols.tolist(), vals.tolist()))

        # Both nodes hold SOME of the field's fragments but not all
        # (otherwise the merge below proves nothing).
        def field_slices(host):
            d = tmp_path / ("a" if host == host_a else "b")
            frag_dir = d / "bc" / "f" / "views" / "field_v" / "fragments"
            return (sorted(int(p) for p in os.listdir(frag_dir))
                    if frag_dir.exists() else [])
        sa, sb = field_slices(host_a), field_slices(host_b)
        assert sa and sb, (sa, sb)
        assert set(sa) | set(sb) == {0, 1, 2, 3}
        assert set(sa) != {0, 1, 2, 3} and set(sb) != {0, 1, 2, 3}

        # Cross-node slice discovery is an async broadcast: wait until
        # node-side Sum counts converge before exact assertions.
        want_count = len(model)
        deadline = time.time() + 20
        while time.time() < deadline:
            got = [_query(h, 'Sum(frame="f", field="v")')[0]["count"]
                   for h in (host_a, host_b)]
            if got == [want_count, want_count]:
                break
            time.sleep(0.3)

        for host in (host_a, host_b):
            s = _query(host, 'Sum(frame="f", field="v")')[0]
            assert s == {"value": sum(model.values()),
                         "count": len(model)}, host
            m = _query(host, 'Min(frame="f", field="v")')[0]
            assert m["value"] == min(model.values()), host
            m = _query(host, 'Max(frame="f", field="v")')[0]
            assert m["value"] == max(model.values()), host
            got = _query(host, 'Range(frame="f", v >= 500)')[0]["bits"]
            assert sorted(got) == sorted(
                c for c, v in model.items() if v >= 500), host
            n = _query(host, 'Count(Range(frame="f", v < 0))')[0]
            assert n == sum(1 for v in model.values() if v < 0), host

        # SetFieldValue through node B for a column node A owns (and
        # vice versa): the write must forward to the owner, and both
        # nodes must see the new value in every aggregate.
        for host in (host_a, host_b):
            c = int(cols[0])
            res = _query(host, f'SetFieldValue(frame="f",'
                               f' columnID={c}, v=777)')
            model[c] = 777
            assert res[0] in (True, False)
            c = int(cols[1])
            res = _query(host, f'SetFieldValue(frame="f",'
                               f' columnID={c}, v=-100)')
            model[c] = -100
        for host in (host_a, host_b):
            s = _query(host, 'Sum(frame="f", field="v")')[0]
            assert s == {"value": sum(model.values()),
                         "count": len(model)}, host
            got = _query(host, 'Range(frame="f", v == 777)')[0]["bits"]
            assert sorted(got) == sorted(
                c for c, v in model.items() if v == 777), host
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGINT)
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
