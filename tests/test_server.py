"""Server runtime integration tests: real servers on real sockets
(reference server/server_test.go MustRunMain: multiple in-process servers
with cross-wired Cluster.Nodes lists, server_test.go:278-496)."""

import json
import time
import urllib.request

import pytest

from pilosa_tpu.cluster.broadcast import HTTPBroadcaster
from pilosa_tpu.cluster.client import Bit, Client
from pilosa_tpu.cluster.topology import Cluster, Node
from pilosa_tpu.server.server import Server
from pilosa_tpu.server.syncer import HolderSyncer


def make_server(tmp_path, name, **kw):
    s = Server(str(tmp_path / name), host="127.0.0.1:0",
               anti_entropy_interval=0, polling_interval=0, **kw)
    return s


def http_get(host, path):
    with urllib.request.urlopen(f"http://{host}{path}", timeout=10) as r:
        return r.status, r.read()


def http_post(host, path, body=b"", content_type="application/json"):
    req = urllib.request.Request(
        f"http://{host}{path}", data=body, method="POST",
        headers={"Content-Type": content_type})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, r.read()


class TestSingleNode:
    @pytest.fixture
    def server(self, tmp_path):
        s = make_server(tmp_path, "s1")
        s.open()
        yield s
        s.close()

    def test_end_to_end_http(self, server):
        host = server.host
        status, _ = http_post(host, "/index/i", b"{}")
        assert status == 200
        status, _ = http_post(host, "/index/i/frame/f", b"{}")
        assert status == 200
        status, body = http_post(
            host, "/index/i/query",
            b'SetBit(frame="f", rowID=1, columnID=3)')
        assert json.loads(body) == {"results": [True]}
        status, body = http_post(host, "/index/i/query",
                                 b'Bitmap(frame="f", rowID=1)')
        assert json.loads(body) == {"results": [{"attrs": {},
                                                 "bits": [3]}]}
        status, body = http_get(host, "/schema")
        assert json.loads(body)["indexes"][0]["name"] == "i"
        status, body = http_get(host, "/status")
        node = json.loads(body)["status"]["nodes"][0]
        assert node["state"] == "UP"
        # Owned-slice knowledge rides the status (server.go:317-321).
        assert node["indexes"][0]["slices"] == [0]

    def test_set_quick_random_bits_survive_restart(self, tmp_path):
        """Randomized property test through the full HTTP stack: random
        SetBits, rows cross-checked before AND after a server restart
        (reference server_test.go:42-121 TestMain_Set_Quick)."""
        import random
        rng = random.Random(42)
        want: dict[int, set[int]] = {}

        s = make_server(tmp_path, "quick")
        s.open()
        host = s.host
        http_post(host, "/index/qi", b"{}")
        http_post(host, "/index/qi/frame/qf", b"{}")
        for _ in range(120):
            row = rng.randrange(8)
            col = rng.randrange(3 * (1 << 20))   # spans three slices
            http_post(host, "/index/qi/query",
                      f'SetBit(frame="qf", rowID={row}, '
                      f'columnID={col})'.encode())
            want.setdefault(row, set()).add(col)

        def check(h):
            for row, cols in want.items():
                _, body = http_post(h, "/index/qi/query",
                                    f'Bitmap(frame="qf", '
                                    f'rowID={row})'.encode())
                got = json.loads(body)["results"][0]["bits"]
                assert got == sorted(cols), (row, got)

        check(host)
        s.close()

        s2 = make_server(tmp_path, "quick")
        s2.open()
        try:
            check(s2.host)
        finally:
            s2.close()

    def test_soak_mixed_mutations_multi_restart(self, tmp_path):
        """Durability soak: three write/verify/restart cycles mixing
        per-op PQL SetBit/ClearBit (WAL appends + MAX_OP_N snapshot
        churn) with bulk imports (snapshot rewrites), cross-checking
        full row contents and exact TopN counts after every restart."""
        import random
        rng = random.Random(7)
        want: dict[int, set[int]] = {r: set() for r in range(6)}

        def check(h):
            for row, cols in want.items():
                _, body = http_post(h, "/index/qi/query",
                                    f'Bitmap(frame="qf", '
                                    f'rowID={row})'.encode())
                assert json.loads(body)["results"][0]["bits"] \
                    == sorted(cols), row
            ids = sorted(want)
            _, body = http_post(h, "/index/qi/query",
                                f'TopN(frame="qf", ids={ids})'.encode())
            got = {p["id"]: p["count"]
                   for p in json.loads(body)["results"][0]}
            assert got == {r: len(c) for r, c in want.items() if c}

        for cycle in range(3):
            s = make_server(tmp_path, "soak")
            s.open()
            host = s.host
            if cycle == 0:
                http_post(host, "/index/qi", b"{}")
                http_post(host, "/index/qi/frame/qf", b"{}")
            check(host)  # previous cycle's state survived the restart
            for _ in range(150):
                row = rng.randrange(6)
                col = rng.randrange(2 * (1 << 20))
                if rng.random() < 0.25 and want[row]:
                    col = rng.choice(sorted(want[row]))
                    http_post(host, "/index/qi/query",
                              f'ClearBit(frame="qf", rowID={row}, '
                              f'columnID={col})'.encode())
                    want[row].discard(col)
                else:
                    http_post(host, "/index/qi/query",
                              f'SetBit(frame="qf", rowID={row}, '
                              f'columnID={col})'.encode())
                    want[row].add(col)
            # One bulk import per cycle: snapshot path, distinct rows
            bulk = [(rng.randrange(6), rng.randrange(2 * (1 << 20)))
                    for _ in range(2000)]
            Client(host).import_bits(
                "qi", "qf", [Bit(r, c) for r, c in bulk])
            for r, c in bulk:
                want[r].add(c)
            check(host)
            s.close()
        s = make_server(tmp_path, "soak")
        s.open()
        try:
            check(s.host)
        finally:
            s.close()

    def test_restart_persists(self, tmp_path):
        s = make_server(tmp_path, "sp")
        s.open()
        host = s.host
        http_post(host, "/index/i", b"{}")
        http_post(host, "/index/i/frame/f", b"{}")
        http_post(host, "/index/i/query",
                  b'SetBit(frame="f", rowID=9, columnID=4)')
        s.close()

        s2 = make_server(tmp_path, "sp")
        s2.open()
        try:
            _, body = http_post(s2.host, "/index/i/query",
                                b'Count(Bitmap(frame="f", rowID=9))')
            assert json.loads(body) == {"results": [1]}
        finally:
            s2.close()

    def test_client_import_and_query(self, server):
        client = Client(server.host)
        client.create_index("i")
        client.create_frame("i", "f")
        client.import_bits("i", "f", [Bit(1, 1), Bit(1, 2), Bit(2, 1)])
        res = client.execute_query(None, "i",
                                   'Count(Bitmap(frame="f", rowID=1))',
                                   remote=False)
        assert res == [2]
        csv = client.export_csv("i", "f", "standard", 0)
        assert csv.splitlines() == ["1,1", "1,2", "2,1"]


def cross_wire(*servers):
    """Make every server's cluster contain all servers' nodes
    (server_test.go:286-290)."""
    nodes = [Node(s.host) for s in servers]
    for s in servers:
        s.cluster.nodes = [Node(n.host) for n in nodes]


class TestTwoNodeCluster:
    @pytest.fixture
    def pair(self, tmp_path):
        s1 = make_server(tmp_path, "n1")
        s2 = make_server(tmp_path, "n2")
        s1.open()
        s2.open()
        cross_wire(s1, s2)
        yield s1, s2
        s1.close()
        s2.close()

    def _create_everywhere(self, servers, index="i", frame="f"):
        for s in servers:
            http_post(s.host, f"/index/{index}", b"{}")
            http_post(s.host, f"/index/{index}/frame/{frame}", b"{}")

    def test_distributed_write_read(self, pair):
        s1, s2 = pair
        self._create_everywhere(pair)
        # Write through node 1; the executor routes to the owner.
        for col in (1, 2, 3):
            status, body = http_post(
                s1.host, "/index/i/query",
                f'SetBit(frame="f", rowID=1, columnID={col})'.encode())
            assert json.loads(body) == {"results": [True]}, body
        # Read through node 2: map-reduce crosses nodes.
        _, body = http_post(s2.host, "/index/i/query",
                            b'Count(Bitmap(frame="f", rowID=1))')
        assert json.loads(body) == {"results": [3]}
        # The bits live on exactly the owner.
        owner = s1.cluster.fragment_nodes("i", 0)[0].host
        owner_server = s1 if owner == s1.host else s2
        assert owner_server.holder.fragment(
            "i", "f", "standard", 0).row(1).count() == 3

    def test_replicated_cluster_random_soak_converges(self, tmp_path):
        """Randomized cluster consistency soak (reference style:
        server_test.go:42-121 quick test, raised to a replicated
        2-node cluster): random SetBit/ClearBit enter through EITHER
        node across 4 slices plus an inverse frame; Bitmap/Count/TopN
        reads from BOTH nodes must match a brute-force model; then a
        deliberately diverged replica must converge through
        anti-entropy to identical fragment checksums."""
        import random
        from pilosa_tpu import SLICE_WIDTH

        s1 = make_server(tmp_path, "r1")
        s2 = make_server(tmp_path, "r2")
        s1.open()
        s2.open()
        try:
            cross_wire(s1, s2)
            for s in (s1, s2):
                s.cluster.replica_n = 2
                http_post(s.host, "/index/i", b"{}")
                http_post(s.host, "/index/i/frame/f", b"{}")
                http_post(s.host, "/index/i/frame/inv",
                          b'{"options": {"inverseEnabled": true}}')

            for s in (s1, s2):
                http_post(s.host, "/index/i/frame/tq",
                          b'{"options": {"timeQuantum": "YMD"}}')

            rng = random.Random(99)
            servers = (s1, s2)
            model: dict[int, set[int]] = {}
            inv_model: dict[int, set[int]] = {}
            ts_model: dict[tuple[int, int], set[int]] = {}  # (row, day)
            for _ in range(600):
                s = servers[rng.randrange(2)]
                row = rng.randrange(6)
                col = rng.randrange(4 * SLICE_WIDTH)
                pick = rng.random()
                if pick < 0.15:
                    # Timestamped write into the time-quantum frame.
                    day = rng.randrange(1, 5)
                    http_post(s.host, "/index/i/query",
                              f'SetBit(frame="tq", rowID={row},'
                              f' columnID={col},'
                              f' timestamp="2017-01-0{day}T00:00")'
                              .encode())
                    ts_model.setdefault((row, day), set()).add(col)
                    continue
                frame, m = (("f", model) if pick < 0.75
                            else ("inv", inv_model))
                if rng.random() < 0.85:
                    http_post(s.host, "/index/i/query",
                              f'SetBit(frame="{frame}", rowID={row},'
                              f' columnID={col})'.encode())
                    m.setdefault(row, set()).add(col)
                else:
                    http_post(s.host, "/index/i/query",
                              f'ClearBit(frame="{frame}", rowID={row},'
                              f' columnID={col})'.encode())
                    m.setdefault(row, set()).discard(col)

            for s in servers:  # both nodes serve identical results
                for row in range(6):
                    want = sorted(model.get(row, ()))
                    _, body = http_post(
                        s.host, "/index/i/query",
                        f'Bitmap(frame="f", rowID={row})'.encode())
                    got = json.loads(body)["results"][0]["bits"]
                    assert got == want, (s.host, row)
                _, body = http_post(
                    s.host, "/index/i/query",
                    b'Count(Union(Bitmap(frame="f", rowID=0),'
                    b' Bitmap(frame="f", rowID=1)))')
                assert json.loads(body)["results"][0] == len(
                    model.get(0, set()) | model.get(1, set()))
                _, body = http_post(s.host, "/index/i/query",
                                    b'TopN(frame="f", n=3)')
                got = [(p["id"], p["count"])
                       for p in json.loads(body)["results"][0]]
                want = sorted(((r, len(c)) for r, c in model.items()
                               if len(c)),
                              key=lambda rc: (-rc[1], rc[0]))[:3]
                assert got == want, (s.host, got, want)
                # Inverse reads: Bitmap(columnID=c) = rows having c.
                inv_cols = {c for cols in inv_model.values()
                            for c in cols}
                for col in sorted(inv_cols)[:5]:
                    _, body = http_post(
                        s.host, "/index/i/query",
                        f'Bitmap(frame="inv", columnID={col})'.encode())
                    got = json.loads(body)["results"][0]["bits"]
                    want = sorted(r for r, cols in inv_model.items()
                                  if col in cols)
                    assert got == want, (s.host, col)
                # Range over the time-view cover, cluster-wide.
                for row in range(6):
                    for lo, hi in ((1, 3), (2, 5), (1, 5)):
                        _, body = http_post(
                            s.host, "/index/i/query",
                            f'Count(Range(rowID={row}, frame="tq",'
                            f' start="2017-01-0{lo}T00:00",'
                            f' end="2017-01-0{hi}T00:00"))'.encode())
                        got = json.loads(body)["results"][0]
                        want = len(set().union(*(
                            ts_model.get((row, d), set())
                            for d in range(lo, hi))))
                        assert got == want, (s.host, row, lo, hi)

            # Replicated writes: every owned fragment exists on both
            # nodes with identical contents already; now diverge one
            # replica directly and let anti-entropy repair it.
            frag2 = s2.holder.fragment("i", "f", "standard", 0)
            if frag2 is not None:
                for col in range(100, 160):
                    frag2.set_bit(5, col)
            HolderSyncer(s1.holder, s1.host, s1.cluster).sync_holder()
            HolderSyncer(s2.holder, s2.host, s2.cluster).sync_holder()
            for slice in range(4):
                f1 = s1.holder.fragment("i", "f", "standard", slice)
                f2 = s2.holder.fragment("i", "f", "standard", slice)
                if f1 is None or f2 is None:
                    assert (f1 is None) == (f2 is None), slice
                    continue
                assert f1.checksum() == f2.checksum(), slice
        finally:
            s1.close()
            s2.close()

    def test_replica_failover_serves_reads(self, tmp_path):
        """ReplicaN=2 over two real servers: writes fan to both owners;
        after one node dies, queries through the survivor re-map the
        dead node's slices onto its replica (executor.go:1137-1151)
        and still return exact results."""
        import random
        s1 = make_server(tmp_path, "f1")
        s2 = make_server(tmp_path, "f2")
        s1.open()
        s2.open()
        try:
            try:
                cross_wire(s1, s2)
                s1.cluster.replica_n = 2
                s2.cluster.replica_n = 2
                self._create_everywhere((s1, s2))
                rng = random.Random(5)
                want: dict[int, set[int]] = {}
                for _ in range(80):
                    row = rng.randrange(4)
                    col = rng.randrange(8 * (1 << 20))
                    http_post(s1.host, "/index/i/query",
                              f'SetBit(frame="f", rowID={row}, '
                              f'columnID={col})'.encode())
                    want.setdefault(row, set()).add(col)
                # The jump hash (index name + slice → node INDEX, port-
                # independent) must give the to-be-killed node at least
                # one primary, or this wouldn't exercise retry.
                primaries = {s1.cluster.fragment_nodes("i", sl)[0].host
                             for sl in range(8)}
                assert s2.host in primaries
            finally:
                s2.close()
            for row, cols in want.items():
                _, body = http_post(
                    s1.host, "/index/i/query",
                    f'Count(Bitmap(frame="f", rowID={row}))'.encode())
                assert json.loads(body) == {"results": [len(cols)]}, row
            _, body = http_post(
                s1.host, "/index/i/query",
                f'TopN(frame="f", ids={sorted(want)})'.encode())
            got = {p["id"]: p["count"]
                   for p in json.loads(body)["results"][0]}
            assert got == {r: len(c) for r, c in want.items()}
        finally:
            s1.close()

    def test_http_broadcast_schema_propagation(self, tmp_path):
        s1 = make_server(tmp_path, "b1")
        s2 = make_server(tmp_path, "b2")
        s1.open()
        s2.open()
        try:
            cross_wire(s1, s2)
            s1.broadcaster = HTTPBroadcaster(s1)
            s1.handler.broadcaster = s1.broadcaster
            # Create via node 1's HTTP API → broadcast → node 2.
            http_post(s1.host, "/index/bidx", b"{}")
            http_post(s1.host, "/index/bidx/frame/bf", b"{}")
            assert s2.holder.index("bidx") is not None
            assert s2.holder.frame("bidx", "bf") is not None
        finally:
            s1.close()
            s2.close()

    def test_gossip_backed_servers_merge_schema(self, tmp_path):
        """Full gossip integration at the Server level (the cmd_server
        wiring): node B joins via seed, learns A's schema through the
        push-pull full-state exchange (server.go:306-387 StatusHandler),
        membership converges both ways, and a later create on B reaches
        A through the gossip broadcast channel."""
        from test_gossip import wait_until

        from pilosa_tpu.cluster.gossip import GossipNodeSet

        def gossip_server(name, seeds):
            # ":0" throughout — Server.open resolves the real port and
            # renames the cluster node AND the node_set host
            # (server.py ":0" rebind), so no pre-picked-port race.
            ns = GossipNodeSet("127.0.0.1:0", gossip_host="127.0.0.1:0",
                               seeds=seeds, probe_interval=0.1,
                               probe_timeout=0.2, push_pull_interval=0.25)
            s = Server(str(tmp_path / name), host="127.0.0.1:0",
                       broadcast_receiver=ns, broadcaster=ns,
                       anti_entropy_interval=0, polling_interval=0)
            s.cluster.node_set = ns
            s.open()
            return s, ns

        sa, ga = gossip_server("ga", [])
        sb = None
        try:
            http_post(sa.host, "/index/gi", b"{}")
            http_post(sa.host, "/index/gi/frame/gf", b"{}")
            sb, gb = gossip_server("gb", [ga.gossip_host])
            assert wait_until(
                lambda: sb.holder.frame("gi", "gf") is not None), \
                "schema did not merge via push-pull"
            want = {sa.host, sb.host}
            assert wait_until(
                lambda: {n.host for n in ga.nodes()} == want
                and {n.host for n in gb.nodes()} == want), \
                "membership did not converge"
            http_post(sb.host, "/index/gj", b"{}")
            assert wait_until(
                lambda: sa.holder.index("gj") is not None), \
                "gossip broadcast did not deliver the create"
        finally:
            if sb is not None:
                sb.close()
            sa.close()

    def test_three_gossip_servers_death_and_revival(self, tmp_path):
        """3 gossip-backed servers: transitive membership through one
        seed, schema everywhere, probe-declared death visible at every
        survivor, and a restarted node (same identity, same data dir)
        rejoining to full membership with its schema intact."""
        from test_gossip import wait_until

        from pilosa_tpu.cluster.gossip import GossipNodeSet

        def gossip_server(name, seeds, host="127.0.0.1:0"):
            ns = GossipNodeSet(host, gossip_host="127.0.0.1:0",
                               seeds=seeds, probe_interval=0.1,
                               probe_timeout=0.2, push_pull_interval=0.25,
                               suspect_after=2)
            s = Server(str(tmp_path / name), host=host,
                       broadcast_receiver=ns, broadcaster=ns,
                       anti_entropy_interval=0, polling_interval=0)
            s.cluster.node_set = ns
            s.open()
            return s, ns

        sa, ga = gossip_server("g3a", [])
        sb, gb = gossip_server("g3b", [ga.gossip_host])
        sc = None
        try:
            sc, gc = gossip_server("g3c", [ga.gossip_host])
            all_sets = (ga, gb, gc)
            want = {sa.host, sb.host, sc.host}
            assert wait_until(
                lambda: all({n.host for n in g.nodes()} == want
                            for g in all_sets), timeout=10.0), \
                "3-node membership did not converge"
            http_post(sc.host, "/index/g3", b"{}")
            http_post(sc.host, "/index/g3/frame/f", b"{}")
            assert wait_until(
                lambda: all(s.holder.frame("g3", "f") is not None
                            for s in (sa, sb, sc)), timeout=10.0), \
                "schema did not reach every node"

            # C dies; both survivors converge on its absence.
            c_host = sc.host
            sc.close()
            sc = None
            survivors = {sa.host, sb.host}
            assert wait_until(
                lambda: {n.host for n in ga.nodes()} == survivors
                and {n.host for n in gb.nodes()} == survivors,
                timeout=15.0), "death did not converge"

            # Revival: same cluster identity and data dir rejoins (the
            # SWIM refutation path), schema still present locally.
            sc, gc = gossip_server("g3c", [ga.gossip_host], host=c_host)
            want = {sa.host, sb.host, sc.host}
            assert wait_until(
                lambda: all({n.host for n in g.nodes()} == want
                            for g in (ga, gb, gc)), timeout=15.0), \
                "revived node did not rejoin everywhere"
            assert sc.holder.frame("g3", "f") is not None
        finally:
            for s in (sa, sb, sc):
                if s is not None:
                    s.close()

    def test_max_slice_polling(self, pair):
        s1, s2 = pair
        self._create_everywhere(pair)
        from pilosa_tpu import SLICE_WIDTH
        col = 2 * SLICE_WIDTH + 7
        _, body = http_post(
            s1.host, "/index/i/query",
            f'SetBit(frame="f", rowID=1, columnID={col})'.encode())
        assert json.loads(body)["results"] == [True]
        s2.poll_max_slices()
        assert s2.holder.index("i").max_slice() == 2

    def test_anti_entropy_repairs_replicas(self, tmp_path):
        s1 = make_server(tmp_path, "a1")
        s2 = make_server(tmp_path, "a2")
        s1.open()
        s2.open()
        try:
            cross_wire(s1, s2)
            for s in (s1, s2):
                s.cluster.replica_n = 2
                http_post(s.host, "/index/i", b"{}")
                http_post(s.host, "/index/i/frame/f", b"{}")
            # Write divergent data DIRECTLY into each holder (bypassing
            # replication) — anti-entropy must converge them.
            s1.holder.frame("i", "f").set_bit("standard", 1, 5)
            s1.holder.frame("i", "f").set_bit("standard", 1, 6)
            s2.holder.frame("i", "f").set_bit("standard", 1, 6)
            s2.holder.frame("i", "f").set_bit("standard", 2, 9)
            s1.holder.index("i").column_attr_store.set_attrs(
                5, {"tag": "x"})

            HolderSyncer(s1.holder, s1.host, s1.cluster).sync_holder()

            # Majority of 2 copies = 1 → union semantics.
            for s in (s1, s2):
                frag = s.holder.fragment("i", "f", "standard", 0)
                assert sorted(int(b) for b in frag.row(1).bits()) == [5, 6]
                assert sorted(int(b) for b in frag.row(2).bits()) == [9]
            # Attr sync pulled to s1; push happens when s2 syncs.
            HolderSyncer(s2.holder, s2.host, s2.cluster).sync_holder()
            assert s2.holder.index("i").column_attr_store.attrs(5) == \
                {"tag": "x"}
        finally:
            s1.close()
            s2.close()

    def test_frame_restore_across_clusters(self, tmp_path):
        # Reference server_test.go:278-342: restore a frame from another
        # cluster.
        src = make_server(tmp_path, "src")
        dst = make_server(tmp_path, "dst")
        src.open()
        dst.open()
        try:
            client = Client(src.host)
            client.create_index("i")
            client.create_frame("i", "f")
            client.import_bits("i", "f", [Bit(1, 1), Bit(1, 2), Bit(3, 5)])

            dclient = Client(dst.host)
            dclient.create_index("i")
            dclient.create_frame("i", "f")
            dclient.restore_frame(src.host, "i", "f")

            res = dclient.execute_query(
                None, "i", 'Count(Bitmap(frame="f", rowID=1))',
                remote=False)
            assert res == [2]
        finally:
            src.close()
            dst.close()


def test_anti_entropy_resurrects_clear_racing_the_sweep(tmp_path):
    """Documents the engine's (reference-faithful) eventual-consistency
    wart that round 5's 60-minute soaks kept hitting: a ClearBit whose
    replica fan-out is mid-flight when the anti-entropy sweep reads the
    block gets UNDONE. With 2 copies the MergeBlock majority is
    (2+1)//2 = 1, so a bit present on EITHER node counts as consensus
    SET (fragment.go:802-920 has the same arithmetic) — the sweep
    re-sets the cleared replica and the next sweep spreads it back.
    Simulated deterministically: clear on one replica only (the
    mid-fan-out state), then run the syncer."""
    from pilosa_tpu.server.syncer import HolderSyncer

    s1 = make_server(tmp_path, "rz1")
    s2 = make_server(tmp_path, "rz2")
    s1.open()
    s2.open()
    try:
        cross_wire(s1, s2)
        for s in (s1, s2):
            s.cluster.replica_n = 2
            http_post(s.host, "/index/i", b"{}")
            http_post(s.host, "/index/i/frame/f", b"{}")
        # Set fans out to both replicas.
        http_post(s1.host, "/index/i/query",
                  b'SetBit(frame="f", rowID=3, columnID=7)')
        for s in (s1, s2):
            _, body = http_post(s.host, "/index/i/query",
                                b'Count(Bitmap(frame="f", rowID=3))')
            assert json.loads(body)["results"][0] == 1
        # Mid-fan-out snapshot of a clear: applied at s1, not yet s2.
        s1.holder.fragment("i", "f", "standard", 0).clear_bit(3, 7)
        # The sweep observes the divergence and resolves SET-biased.
        HolderSyncer(s1.holder, s1.host, s1.cluster).sync_holder()
        for s in (s1, s2):
            _, body = http_post(s.host, "/index/i/query",
                                b'Count(Bitmap(frame="f", rowID=3))')
            assert json.loads(body)["results"][0] == 1, \
                f"{s.host}: expected the set-biased resurrection"
    finally:
        s2.close()
        s1.close()
