"""Randomized differential: PQL trees lowered through the EXECUTOR
onto the virtual 8-device mesh vs the host roaring path, bit-for-bit.

Covers the acceptance leg of ROADMAP item 1 / ISSUE 6: random
Count(Intersect/Union/Difference) trees, TopN exact-count forms, BSI
``Range`` compare-select circuits (materialized AND under Count, where
they compose with the fused count lane), and multi-op queries that
lower through the fused-tree program — every answer must equal the
host executor's exactly. Same index, same seeds, two executors; any
divergence is a device-lowering bug by construction."""

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder


N_SLICES = 8
N_ROWS = 6
FIELD_MIN, FIELD_MAX = -20, 500


def _norm(results):
    """Executor results → comparable plain values (Bitmap → bit list,
    Pair list → (id, count) list)."""
    out = []
    for r in results:
        if hasattr(r, "bits"):
            out.append(list(r.bits()))
        elif isinstance(r, list):
            out.append([(p.id, p.count) for p in r])
        else:
            out.append(r)
    return out


@pytest.fixture(scope="module")
def holder(tmp_path_factory):
    rng = np.random.default_rng(4242)
    holder = Holder(str(tmp_path_factory.mktemp("devdiff")))
    holder.open()
    idx = holder.create_index("d")
    frame = idx.create_frame("f")
    # Mixed densities: each row dense in one slice, sparse elsewhere —
    # exercises both the sparse-upload densify path and the dense pack.
    for row in range(N_ROWS):
        dense = int(rng.integers(N_SLICES))
        cols = rng.choice(SLICE_WIDTH // 32, size=400, replace=False)
        frame.import_bits(
            np.full(len(cols), row, dtype=np.uint64),
            (cols + dense * SLICE_WIDTH).astype(np.uint64))
        cols = rng.choice(N_SLICES * SLICE_WIDTH, size=80,
                          replace=False)
        frame.import_bits(np.full(len(cols), row, dtype=np.uint64),
                          cols.astype(np.uint64))
    # A run-heavy frame (timestamp-view shape: long dense column
    # spans): the import optimize() pass stores these rows as run
    # containers, so every device leg over it exercises the
    # runs → bit-plane decode on the residency upload path.
    runf = idx.create_frame("rf")
    for row in range(N_ROWS):
        start = int(rng.integers(0, (N_SLICES - 1) * SLICE_WIDTH))
        span = np.arange(start, start + 40000, dtype=np.uint64)
        runf.import_bits(np.full(len(span), row, dtype=np.uint64), span)
    frag0 = holder.fragment("d", "rf", "standard", 0)
    assert frag0 is not None and \
        frag0.container_stats()["counts"]["run"] > 0
    # A BSI field with values spread over every slice (negative min:
    # the offset-space clamp paths matter).
    from pilosa_tpu.models.frame import Field
    frame.create_field(Field("v", FIELD_MIN, FIELD_MAX))
    host = Executor(holder, host="local", use_mesh=False)
    cols = rng.choice(N_SLICES * SLICE_WIDTH, size=600, replace=False)
    vals = rng.integers(FIELD_MIN, FIELD_MAX + 1, size=len(cols))
    for col, val in zip(cols.tolist(), vals.tolist()):
        host.execute("d", f"SetFieldValue(frame=f, columnID={col},"
                          f" v={val})")
    yield holder
    holder.close()


@pytest.fixture(scope="module")
def executors(holder):
    fast = Executor(holder, host="local", use_mesh=True,
                    mesh_min_slices=1)
    slow = Executor(holder, host="local", use_mesh=False)
    yield fast, slow
    assert fast.device_fallbacks == 0
    fast.close()
    slow.close()


def _rand_tree(rng, depth):
    if depth == 0 or rng.random() < 0.35:
        if rng.random() < 0.3:
            op = rng.choice(["<", "<=", ">", ">=", "==", "!="])
            v = int(rng.integers(FIELD_MIN - 5, FIELD_MAX + 6))
            return f"Range(frame=f, v {op} {v})"
        return f"Bitmap(rowID={int(rng.integers(N_ROWS + 1))}, frame=f)"
    op = rng.choice(["Intersect", "Union", "Difference"])
    k = int(rng.integers(2, 4))
    return f"{op}({', '.join(_rand_tree(rng, depth - 1) for _ in range(k))})"


class TestRandomizedDeviceDifferential:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_count_trees(self, executors, seed):
        fast, slow = executors
        rng = np.random.default_rng(seed)
        for _ in range(12):
            q = f"Count({_rand_tree(rng, 2)})"
            assert fast.execute("d", q) == slow.execute("d", q), q

    @pytest.mark.parametrize("seed", [4, 5])
    def test_materialized_range_and_folds(self, executors, seed):
        """BSI Range materialization (the one-program comparison
        circuit) and wide folds over mixed leaves, fetched as bitmaps."""
        fast, slow = executors
        rng = np.random.default_rng(seed)
        for _ in range(8):
            q = _rand_tree(rng, 1)
            got = _norm(fast.execute("d", q))
            want = _norm(slow.execute("d", q))
            assert got == want, q

    @pytest.mark.parametrize("seed", [6, 7])
    def test_topn_exact_forms(self, executors, seed):
        fast, slow = executors
        rng = np.random.default_rng(seed)
        for _ in range(8):
            ids = sorted(set(int(x) for x in
                             rng.integers(N_ROWS + 1, size=4)))
            q = (f"TopN({_rand_tree(rng, 1)}, frame=f, n=5,"
                 f" ids={list(ids)})")
            got = _norm(fast.execute("d", q))
            want = _norm(slow.execute("d", q))
            assert got == want, q

    @pytest.mark.parametrize("seed", [8, 9])
    def test_multi_op_trees_fuse_and_agree(self, executors, seed):
        """Whole multi-call queries — Counts (some over BSI circuits)
        interleaved with exact-count TopNs — lower through the fused
        device program; results must equal per-call host execution."""
        fast, slow = executors
        rng = np.random.default_rng(seed)
        for _ in range(6):
            parts = []
            for _ in range(int(rng.integers(2, 5))):
                if rng.random() < 0.3:
                    ids = sorted(set(int(x) for x in
                                     rng.integers(N_ROWS, size=3)))
                    parts.append(f"TopN({_rand_tree(rng, 1)}, frame=f,"
                                 f" ids={list(ids)})")
                else:
                    parts.append(f"Count({_rand_tree(rng, 1)})")
            q = " ".join(parts)
            got = _norm(fast.execute("d", q))
            want = _norm(slow.execute("d", q))
            assert got == want, q

    @pytest.mark.parametrize("seed", [10, 11])
    def test_run_backed_fragments_device_vs_host(self, executors, seed):
        """Random trees over the run-container-backed frame: the
        residency upload decodes runs to bit-plane slabs, and every
        device answer must equal the host roaring-over-runs answer."""
        fast, slow = executors
        rng = np.random.default_rng(seed)

        def leaf(_rng, _depth=None):
            return (f"Bitmap(rowID={int(_rng.integers(N_ROWS + 1))},"
                    f" frame=rf)")

        for _ in range(8):
            op = rng.choice(["Intersect", "Union", "Difference"])
            q = f"Count({op}({leaf(rng)}, {leaf(rng)}))"
            assert fast.execute("d", q) == slow.execute("d", q), q
        ids = list(range(N_ROWS))
        q = f"TopN({leaf(rng)}, frame=rf, n=4, ids={ids})"
        assert _norm(fast.execute("d", q)) == \
            _norm(slow.execute("d", q)), q

    def test_sourceless_topn_in_program_topk(self, executors,
                                             monkeypatch):
        """The sourceless TopN refetch phase lowers to the in-program
        top-k program (mesh.topn_topk_sharded): same pairs as the host
        two-phase path, and the device leg must actually dispatch."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.parallel import mesh as mesh_mod
        fast, slow = executors
        # Force the two-phase path (the rank-cache single-pass answer
        # would otherwise serve both executors host-side).
        monkeypatch.setattr(Executor, "_topn_host_single_pass",
                            lambda self, *a, **k: None)
        calls = []
        real = mesh_mod.topn_topk_sharded

        def spy(*a, **k):
            calls.append(a)
            return real(*a, **k)

        monkeypatch.setattr(mesh_mod, "topn_topk_sharded", spy)
        for frame, n in (("f", 3), ("rf", 4), ("f", 0)):
            q = f"TopN(frame={frame}, n={n})" if n else \
                f"TopN(frame={frame})"
            got = _norm(fast.execute("d", q))
            want = _norm(slow.execute("d", q))
            assert got == want, q
        assert calls, "device top-k program never dispatched"

    def test_range_between_and_aggregates(self, executors):
        """The >< (between) circuit and Sum's fused plane-count lane."""
        fast, slow = executors
        for lo, hi in ((-20, 0), (0, 250), (100, 500), (-5, 505)):
            q = f"Count(Range(frame=f, v >< [{lo},{hi}]))"
            assert fast.execute("d", q) == slow.execute("d", q), q
        for q in ("Sum(frame=f, field=\"v\")",
                  "Sum(Bitmap(rowID=0, frame=f), frame=f,"
                  " field=\"v\")"):
            assert fast.execute("d", q) == slow.execute("d", q), q
