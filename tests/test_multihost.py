"""Multi-host layer tests (single-process forms).

Real pod hardware isn't available; what IS testable: the local-shard →
global-array assembly and the pod-wide count/topn programs in their
1-process degenerate form (same code path, process_count()==1), plus
jax.distributed bootstrap in a subprocess so its global state can't
leak into this suite.
"""

import os
import subprocess
import sys

import numpy as np

from pilosa_tpu.parallel import mesh as mesh_mod
from pilosa_tpu.parallel import multihost


def _popcount(a):
    return int(np.bitwise_count(a).sum())


class TestSingleProcessForms:
    def test_initialize_without_env_is_noop(self, monkeypatch):
        monkeypatch.delenv("PILOSA_TPU_DIST_COORDINATOR", raising=False)
        assert multihost.initialize_from_env() is False

    def test_process_slice_range_whole_axis(self):
        # 1-process degenerate form: the whole axis belongs to us.
        lo, hi = multihost.process_slice_range(16)
        assert (lo, hi) == (0, 16)

    def test_count_matches_single_host_path(self):
        rng = np.random.default_rng(0)
        mesh = multihost.pod_mesh()
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        S, W = n_dev * 2, 256
        leaves = rng.integers(0, 2**32, size=(2, S, W), dtype=np.uint32)
        expr = ("and", ("leaf", 0), ("leaf", 1))
        got = multihost.count_expr(mesh, expr, leaves)
        assert got == mesh_mod.count_expr(mesh, expr, leaves)
        assert got == _popcount(leaves[0] & leaves[1])

    def test_topn_matches_single_host_path(self):
        rng = np.random.default_rng(1)
        mesh = multihost.pod_mesh()
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        S, R, W = n_dev * 2, 5, 128
        rows = rng.integers(0, 2**32, size=(S, R, W), dtype=np.uint32)
        src = rng.integers(0, 2**32, size=(1, S, W), dtype=np.uint32)
        got = multihost.topn_exact(mesh, ("leaf", 0), rows, src)
        assert got == mesh_mod.topn_exact(mesh, ("leaf", 0), rows, src)
        want = [_popcount(rows[:, r, :] & src[0]) for r in range(R)]
        assert got == want

    def test_count_exprs_batch_matches_singles(self):
        rng = np.random.default_rng(3)
        mesh = multihost.pod_mesh()
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        S, W = n_dev * 2, 256
        leaves = rng.integers(0, 2**32, size=(3, S, W), dtype=np.uint32)
        exprs = (("leaf", 0),
                 ("and", ("leaf", 0), ("leaf", 1)),
                 ("or", ("leaf", 1), ("leaf", 2)))
        got = multihost.count_exprs(mesh, exprs, leaves)
        assert got == [multihost.count_expr(mesh, e, leaves)
                       for e in exprs]

    def test_topn_filtered_matches_single_host_path(self):
        rng = np.random.default_rng(2)
        mesh = multihost.pod_mesh()
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        S, R, W = n_dev * 2, 5, 128
        rows = rng.integers(0, 2**32, size=(S, R, W), dtype=np.uint32)
        src = rng.integers(0, 2**32, size=(1, S, W), dtype=np.uint32)
        for threshold, tanimoto in ((3, 0), (W * 16, 0), (1, 40)):
            got = multihost.topn_exact(mesh, ("leaf", 0), rows, src,
                                       threshold=threshold,
                                       tanimoto=tanimoto)
            assert got == mesh_mod.topn_exact(
                mesh, ("leaf", 0), rows, src,
                threshold=threshold, tanimoto=tanimoto), \
                (threshold, tanimoto)


class TestDistributedBootstrap:
    def test_one_process_pod_in_subprocess(self):
        """jax.distributed.initialize + pod count, isolated subprocess."""
        code = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import sys
sys.path.insert(0, %r)
from pilosa_tpu.parallel import multihost
assert multihost.initialize_from_env() is True
assert jax.process_count() == 1
mesh = multihost.pod_mesh()
S = mesh.shape["slices"] * 2
leaves = np.ones((1, S, 64), dtype=np.uint32)
lo, hi = multihost.process_slice_range(S)
assert (lo, hi) == (0, S)
got = multihost.count_expr(mesh, ("leaf", 0), leaves[:, lo:hi])
assert got == S * 64, got
print("POD OK", got)
"""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        import socket
        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            port = sk.getsockname()[1]
        env = dict(os.environ)
        env.update({
            "PILOSA_TPU_DIST_COORDINATOR": f"127.0.0.1:{port}",
            "PILOSA_TPU_DIST_NUM_PROCS": "1",
            "PILOSA_TPU_DIST_PROC_ID": "0",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        })
        out = None
        for _attempt in range(2):  # retry once on coordinator-port races
            out = subprocess.run([sys.executable, "-c", code % repo],
                                 capture_output=True, text=True, env=env,
                                 timeout=240)
            if out.returncode == 0:
                break
            with socket.socket() as sk:
                sk.bind(("127.0.0.1", 0))
                env["PILOSA_TPU_DIST_COORDINATOR"] = \
                    f"127.0.0.1:{sk.getsockname()[1]}"
        assert out.returncode == 0, out.stderr[-2000:]
        assert "POD OK" in out.stdout
