"""The native-threaded HTTP/1.1 front door (server/httpd.py): keep-alive,
pipelining, the query batch lane's partial-failure semantics, restart
rebinding, and streamed (close-delimited) responses.

Reference analogue: net/http serving per-connection goroutines
(server.go:146)."""

import json
import socket
import tempfile
import time

import pytest

from pilosa_tpu.server.server import Server


def _req(method: str, path: str, body: bytes = b"") -> bytes:
    return (f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
            f"Content-Length: {len(body)}\r\n\r\n").encode() + body


def _read_responses(sock: socket.socket, n: int, timeout=5.0) -> list[str]:
    """Read exactly n HTTP responses (Content-Length framed)."""
    sock.settimeout(timeout)
    buf = b""
    out = []
    while len(out) < n:
        while True:
            head_end = buf.find(b"\r\n\r\n")
            if head_end < 0:
                break
            head = buf[:head_end].decode("latin-1")
            length = 0
            for ln in head.split("\r\n")[1:]:
                k, _, v = ln.partition(":")
                if k.lower() == "content-length":
                    length = int(v)
            total = head_end + 4 + length
            if len(buf) < total:
                break
            out.append(buf[:total].decode("latin-1"))
            buf = buf[total:]
            if len(out) == n:
                return out
        data = sock.recv(1 << 20)
        if not data:
            raise ConnectionError(f"short: got {len(out)}/{n}")
        buf += data
    return out


@pytest.fixture
def server():
    with tempfile.TemporaryDirectory() as d:
        srv = Server(d, host="127.0.0.1:0", anti_entropy_interval=0,
                     polling_interval=0)
        srv.open()
        yield srv
        srv.close()


def _conn(srv) -> socket.socket:
    host, port = srv.host.split(":")
    s = socket.create_connection((host, int(port)))
    return s


def _setup_schema(s: socket.socket) -> None:
    s.sendall(_req("POST", "/index/i") + _req("POST", "/index/i/frame/f"))
    _read_responses(s, 2)


def test_keepalive_many_requests_one_connection(server):
    s = _conn(server)
    try:
        _setup_schema(s)
        for i in range(20):
            s.sendall(_req("POST", "/index/i/query",
                           f'SetBit(frame="f", rowID=1, columnID={i})'
                           .encode()))
            (resp,) = _read_responses(s, 1)
            assert resp.startswith("HTTP/1.1 200")
            assert '"results": [true]' in resp
    finally:
        s.close()


def test_pipelined_batch_lane_results_align(server):
    s = _conn(server)
    try:
        _setup_schema(s)
        blob = b"".join(
            _req("POST", "/index/i/query",
                 f'SetBit(frame="f", rowID=2, columnID={i})'.encode())
            for i in range(50))
        blob += _req("POST", "/index/i/query",
                     b'Count(Bitmap(frame="f", rowID=2))')
        s.sendall(blob)
        resps = _read_responses(s, 51)
        for r in resps[:50]:
            assert '"results": [true]' in r
        assert '"results": [50]' in resps[50]
    finally:
        s.close()


def test_batch_lane_partial_failure_semantics(server):
    """q1 sets a NEW bit, q2 hits a missing frame, q3 sets another new
    bit. The batch lane must report q1 true (never re-executed — a
    re-run would say false), q2 the same 400 the per-request path
    gives, q3 true."""
    s = _conn(server)
    try:
        _setup_schema(s)
        s.sendall(
            _req("POST", "/index/i/query",
                 b'SetBit(frame="f", rowID=5, columnID=1)')
            + _req("POST", "/index/i/query",
                   b'SetBit(frame="nope", rowID=1, columnID=1)')
            + _req("POST", "/index/i/query",
                   b'SetBit(frame="f", rowID=5, columnID=2)'))
        r1, r2, r3 = _read_responses(s, 3)
        assert r1.startswith("HTTP/1.1 200") and '[true]' in r1
        assert r2.startswith("HTTP/1.1 400")
        assert json.loads(r2[r2.find("\r\n\r\n") + 4:])["error"] == "nope"
        assert r3.startswith("HTTP/1.1 200") and '[true]' in r3
    finally:
        s.close()


def test_rebind_same_port_after_close(server):
    host, port = server.host.split(":")
    s = _conn(server)
    _setup_schema(s)  # leave a keep-alive connection dangling
    data_dir = server.data_dir
    server.close()
    srv2 = Server(data_dir, host=f"{host}:{port}",
                  anti_entropy_interval=0, polling_interval=0)
    srv2.open()  # must not raise EADDRINUSE
    try:
        s2 = _conn(srv2)
        try:
            s2.sendall(_req("POST", "/index/i/query",
                            b'Count(Bitmap(frame="f", rowID=1))'))
            (resp,) = _read_responses(s2, 1)
            assert resp.startswith("HTTP/1.1 200")
        finally:
            s2.close()
    finally:
        srv2.close()
        s.close()


def test_streamed_export_close_delimited(server):
    s = _conn(server)
    _setup_schema(s)
    s.sendall(_req("POST", "/index/i/query",
                   b'SetBit(frame="f", rowID=9, columnID=3)'))
    _read_responses(s, 1)
    s.sendall((b"GET /export?index=i&frame=f&view=standard&slice=0"
               b" HTTP/1.1\r\nHost: x\r\nAccept: text/csv\r\n"
               b"Content-Length: 0\r\n\r\n"))
    s.settimeout(5.0)
    buf = b""
    while True:
        data = s.recv(65536)
        if not data:
            break  # close-delimited
        buf += data
    text = buf.decode()
    assert text.startswith("HTTP/1.1 200")
    assert "Connection: close" in text
    assert "9,3" in text
    s.close()


def test_malformed_request_gets_400(server):
    s = _conn(server)
    try:
        s.sendall(b"NONSENSE\r\n\r\n")
        s.settimeout(5.0)
        data = s.recv(65536).decode("latin-1")
        assert data.startswith("HTTP/1.1 400")
    finally:
        s.close()


def test_concurrent_connections_mixed_load(server):
    """Many connections driving reads and writes at once: the
    thread-per-connection server must keep responses framed per
    connection with no cross-talk (each connection writes rows only it
    writes, then reads its own count back)."""
    import threading

    s0 = _conn(server)
    _setup_schema(s0)
    s0.close()
    errs: list = []

    def worker(wid: int):
        try:
            s = _conn(server)
            try:
                for i in range(30):
                    s.sendall(_req(
                        "POST", "/index/i/query",
                        f'SetBit(frame="f", rowID={100 + wid},'
                        f' columnID={i})'.encode()))
                    (r,) = _read_responses(s, 1)
                    assert '"results": [true]' in r, r[-120:]
                s.sendall(_req(
                    "POST", "/index/i/query",
                    f'Count(Bitmap(frame="f", rowID={100 + wid}))'
                    .encode()))
                (r,) = _read_responses(s, 1)
                assert '"results": [30]' in r, (wid, r[-120:])
            finally:
                s.close()
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append((wid, e))

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "worker hung"
    assert not errs, errs[:3]


def test_fuzz_pipelined_equals_sequential(server):
    """Property test: a random pipelined stream of valid and invalid
    queries over one connection must produce byte-for-byte the same
    (status, results) sequence as the same stream sent one request at
    a time on a fresh server — batching is an invisible optimization."""
    import random

    rng = random.Random(1234)

    def rand_stream(n):
        out = []
        for _ in range(n):
            kind = rng.random()
            if kind < 0.55:
                out.append(f'SetBit(frame="f", rowID={rng.randrange(6)},'
                           f' columnID={rng.randrange(2000)})')
            elif kind < 0.65:
                out.append(f'ClearBit(frame="f", rowID={rng.randrange(6)},'
                           f' columnID={rng.randrange(2000)})')
            elif kind < 0.75:
                out.append(f'Count(Bitmap(frame="f",'
                           f' rowID={rng.randrange(6)}))')
            elif kind < 0.85:
                out.append('TopN(frame="f", n=3)')
            elif kind < 0.93:
                out.append(f'SetBit(frame="missing",'
                           f' rowID=1, columnID={rng.randrange(99)})')
            else:
                out.append("Union(")  # parse error
        return out

    def normalize(resp: str) -> tuple:
        status = resp.split(" ", 2)[1]
        body = resp[resp.find("\r\n\r\n") + 4:]
        return (status, body)

    stream = rand_stream(120)
    s = _conn(server)
    _setup_schema(s)
    # pipelined: all at once
    s.sendall(b"".join(_req("POST", "/index/i/query", q.encode())
                       for q in stream))
    piped = [normalize(r) for r in _read_responses(s, len(stream),
                                                   timeout=30.0)]
    s.close()

    # sequential on a fresh server (same data dir shape)
    with tempfile.TemporaryDirectory() as d2:
        srv2 = Server(d2, host="127.0.0.1:0", anti_entropy_interval=0,
                      polling_interval=0)
        srv2.open()
        try:
            s2 = _conn(srv2)
            _setup_schema(s2)
            seq = []
            for q in stream:
                s2.sendall(_req("POST", "/index/i/query", q.encode()))
                (r,) = _read_responses(s2, 1, timeout=30.0)
                seq.append(normalize(r))
            s2.close()
        finally:
            srv2.close()
    assert piped == seq, next(
        ((i, a, b) for i, (a, b) in enumerate(zip(piped, seq))
         if a != b), ("len", len(piped), len(seq)))


def test_batch_lane_count_of_one_stays_numeric(server):
    """Regression: Python's [1] == [True], so a naive cached-payload
    fast path would rewrite a Count result of exactly 1 into JSON
    `true` on the batch lane (review r5). Counts must stay numbers."""
    s = _conn(server)
    try:
        _setup_schema(s)
        s.sendall(_req("POST", "/index/i/query",
                       b'SetBit(frame="f", rowID=4, columnID=9)'))
        _read_responses(s, 1)
        # Two pipelined requests so the batch lane engages.
        s.sendall(_req("POST", "/index/i/query",
                       b'Count(Bitmap(frame="f", rowID=4))')
                  + _req("POST", "/index/i/query",
                         b'Count(Bitmap(frame="f", rowID=99))'))
        r1, r2 = _read_responses(s, 2)
        assert '"results": [1]' in r1, r1[-80:]
        assert '"results": [0]' in r2, r2[-80:]
    finally:
        s.close()


def test_large_body_in_small_chunks(server):
    """Round-5 regression: the parser re-scanned the whole receive
    buffer for the header terminator on every recv — quadratic on
    multi-MB bodies. A large raw-format import delivered in small
    chunks by a slow client must parse once, apply, and stay fast."""
    import numpy as np

    from pilosa_tpu.proto import rawimport

    with _conn(server) as s:
        s.sendall(_req("POST", "/index/big", b"{}"))
        _read_responses(s, 1)
        s.sendall(_req("POST", "/index/big/frame/f", b"{}"))
        _read_responses(s, 1)
        rows = np.arange(200_000, dtype=np.uint64) % np.uint64(50)
        cols = np.arange(200_000, dtype=np.uint64) * np.uint64(5) \
            % np.uint64(1 << 20)
        payload = rawimport.encode("big", "f", 0, rows, cols, None)
        head = (f"POST /import HTTP/1.1\r\nHost: x\r\n"
                f"Content-Type: {rawimport.CONTENT_TYPE}\r\n"
                f"Accept: application/x-protobuf\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n").encode()
        blob = head + payload
        t0 = time.time()
        # 64 KB chunks with a yield between sends: the server's fill
        # loop sees many partial reads of the one request.
        for i in range(0, len(blob), 1 << 16):
            s.sendall(blob[i:i + (1 << 16)])
            time.sleep(0)
        resp = _read_responses(s, 1, timeout=30.0)[0]
        assert "200" in resp.split("\r\n")[0]
        assert time.time() - t0 < 20.0
        s.sendall(_req("POST", "/index/big/query",
                       b'Count(Bitmap(rowID=7, frame="f"))'))
        body = _read_responses(s, 1)[0]
        want = len({int(c) for r, c in zip(rows.tolist(), cols.tolist())
                    if r == 7})
        assert f"[{want}]" in body
