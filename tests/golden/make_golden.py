"""Generate golden interchange fixtures in the reference wire format.

These bytes are hand-assembled with struct.pack from the DOCUMENTED
layout of the reference file format (roaring.go:475-614 for the
snapshot body, roaring.go:1560-1626 for op records) — deliberately
independent of pilosa_tpu.storage.roaring, so the tests in
tests/test_golden.py prove interchange against the format itself, not
against our own serializer reading its own output.

Layout (all little-endian):
  snapshot := cookie(u32 = 12346) containerN(u32)
              [key(u64) n_minus_1(u32)] * containerN
              [offset(u32)] * containerN
              container blocks: array (n ≤ 4096): n × u32 (low 16 bits)
                                bitmap (n > 4096): 1024 × u64
  op       := typ(u8: 0=add, 1=remove) value(u64) fnv1a32(of first 9B)(u32)

Run ``python tests/golden/make_golden.py`` to (re)write the fixtures;
test_golden.py asserts the committed bytes match this generator, so the
fixtures cannot rot silently.
"""

import os
import struct

COOKIE = 12346
ARRAY_MAX = 4096
HERE = os.path.dirname(os.path.abspath(__file__))


def fnv1a32(data: bytes) -> int:
    h = 0x811C9DC5
    for byte in data:
        h ^= byte
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def snapshot(containers: list[tuple[int, list[int]]]) -> bytes:
    """containers: sorted [(key, sorted low-16-bit values)]."""
    header = struct.pack("<II", COOKIE, len(containers))
    keys = b""
    blocks = []
    for key, vals in containers:
        assert vals == sorted(set(vals)) and all(0 <= v < 65536
                                                 for v in vals)
        keys += struct.pack("<QI", key, len(vals) - 1)
        if len(vals) <= ARRAY_MAX:
            blocks.append(struct.pack(f"<{len(vals)}I", *vals))
        else:
            words = [0] * 1024
            for v in vals:
                words[v >> 6] |= 1 << (v & 63)
            blocks.append(struct.pack("<1024Q", *words))
    offsets = b""
    off = len(header) + len(keys) + 4 * len(containers)
    for blk in blocks:
        offsets += struct.pack("<I", off)
        off += len(blk)
    return header + keys + offsets + b"".join(blocks)


def op(typ: int, value: int) -> bytes:
    body = struct.pack("<BQ", typ, value)
    return body + struct.pack("<I", fnv1a32(body))


def fixtures() -> dict[str, bytes]:
    """name → hand-assembled bytes for every fixture."""
    out = {
        "empty.roaring": snapshot([]),
        "simple_array.roaring": snapshot([(0, SIMPLE_VALUES)]),
        "multi_container.roaring": snapshot([
            (0, list(range(10))),
            (1, BITMAP_LOWS),
            (HIGH_KEY, [123]),
        ]),
    }
    # Snapshot + appended op log (the on-disk WAL form a fragment file
    # has between snapshots, fragment.go:179-234).
    out["with_oplog.roaring"] = (
        out["simple_array.roaring"] + b"".join(op(t, v) for t, v in OPS))
    # The same logical bitmap in canonical snapshot form (what a
    # post-replay re-serialization must produce).
    replayed = sorted({v for v in SIMPLE_VALUES if v != 100}
                      | {5, 42, 2 * 65536 + 7})
    by_key: dict[int, list[int]] = {}
    for v in replayed:
        by_key.setdefault(v >> 16, []).append(v & 0xFFFF)
    out["with_oplog.expected.roaring"] = snapshot(sorted(by_key.items()))
    return out


# Fixture bit sets, kept in sync with tests/test_golden.py.
SIMPLE_VALUES = [1, 5, 100, 65535]
BITMAP_LOWS = list(range(0, 10000, 2))       # 5000 values → bitmap kind
HIGH_KEY = 1 << 21                           # a 48-bit container key
OPS = [(0, 2 * 65536 + 7), (0, 5), (1, 100), (0, 42)]  # add/add/rm/add


def main(out_dir: str = HERE) -> None:
    for name, data in fixtures().items():
        with open(os.path.join(out_dir, name), "wb") as f:
            f.write(data)
        print(f"{name}: {len(data)} bytes")


if __name__ == "__main__":
    import sys
    main(sys.argv[1] if len(sys.argv) > 1 else HERE)
