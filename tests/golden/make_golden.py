"""Generate golden interchange fixtures in the reference wire format.

These bytes are hand-assembled with struct.pack from the DOCUMENTED
layout of the reference file format (roaring.go:475-614 for the
snapshot body, roaring.go:1560-1626 for op records) — deliberately
independent of pilosa_tpu.storage.roaring, so the tests in
tests/test_golden.py prove interchange against the format itself, not
against our own serializer reading its own output.

Layout (all little-endian):
  snapshot := cookie(u32 = 12346) containerN(u32)
              [key(u64) n_minus_1(u32)] * containerN
              [offset(u32)] * containerN
              container blocks: array (n ≤ 4096): n × u32 (low 16 bits)
                                bitmap (n > 4096): 1024 × u64
  op       := typ(u8: 0=add, 1=remove) value(u64) fnv1a32(of first 9B)(u32)

Runs format (cookie 12347 — the SERIAL_COOKIE idiom of the optimized
Roaring library paper, arXiv:1709.07821): identical except a run-flag
bitset sits between containerN and the headers — ceil(containerN/8)
bytes rounded up to a multiple of 8, little-endian bit order, bit i
set ⇒ container i is a run container — and a flagged container's
block is numRuns(u16) followed by numRuns (start u16, length-1 u16)
pairs. Headers still carry cardinality-1. A snapshot with no run
container MUST use cookie 12346 (byte-compatible with the vintage).

Run ``python tests/golden/make_golden.py`` to (re)write the fixtures;
test_golden.py asserts the committed bytes match this generator, so the
fixtures cannot rot silently.
"""

import os
import struct

COOKIE = 12346
COOKIE_RUNS = 12347
ARRAY_MAX = 4096
HERE = os.path.dirname(os.path.abspath(__file__))


def _runs_of(vals: list[int]) -> list[tuple[int, int]]:
    """[(start, length)] runs of a sorted value list."""
    runs = []
    for v in vals:
        if runs and v == runs[-1][0] + runs[-1][1]:
            runs[-1] = (runs[-1][0], runs[-1][1] + 1)
        else:
            runs.append((v, 1))
    return runs


def snapshot_runs(containers: list[tuple[int, list[int], bool]]) -> bytes:
    """Runs-cookie snapshot. containers: sorted
    [(key, sorted low-16-bit values, as_run)] — ``as_run`` containers
    serialize as interval blocks and set their flag bit."""
    n = len(containers)
    header = struct.pack("<II", COOKIE_RUNS, n)
    flag_len = ((n + 7) // 8 + 7) // 8 * 8
    flags = bytearray(flag_len)
    keys = b""
    blocks = []
    for i, (key, vals, as_run) in enumerate(containers):
        assert vals == sorted(set(vals)) and all(0 <= v < 65536
                                                 for v in vals)
        keys += struct.pack("<QI", key, len(vals) - 1)
        if as_run:
            flags[i >> 3] |= 1 << (i & 7)
            runs = _runs_of(vals)
            blk = struct.pack("<H", len(runs))
            for start, length in runs:
                blk += struct.pack("<HH", start, length - 1)
            blocks.append(blk)
        elif len(vals) <= ARRAY_MAX:
            blocks.append(struct.pack(f"<{len(vals)}I", *vals))
        else:
            words = [0] * 1024
            for v in vals:
                words[v >> 6] |= 1 << (v & 63)
            blocks.append(struct.pack("<1024Q", *words))
    offsets = b""
    off = len(header) + flag_len + len(keys) + 4 * n
    for blk in blocks:
        offsets += struct.pack("<I", off)
        off += len(blk)
    return header + bytes(flags) + keys + offsets + b"".join(blocks)


def fnv1a32(data: bytes) -> int:
    h = 0x811C9DC5
    for byte in data:
        h ^= byte
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def snapshot(containers: list[tuple[int, list[int]]]) -> bytes:
    """containers: sorted [(key, sorted low-16-bit values)]."""
    header = struct.pack("<II", COOKIE, len(containers))
    keys = b""
    blocks = []
    for key, vals in containers:
        assert vals == sorted(set(vals)) and all(0 <= v < 65536
                                                 for v in vals)
        keys += struct.pack("<QI", key, len(vals) - 1)
        if len(vals) <= ARRAY_MAX:
            blocks.append(struct.pack(f"<{len(vals)}I", *vals))
        else:
            words = [0] * 1024
            for v in vals:
                words[v >> 6] |= 1 << (v & 63)
            blocks.append(struct.pack("<1024Q", *words))
    offsets = b""
    off = len(header) + len(keys) + 4 * len(containers)
    for blk in blocks:
        offsets += struct.pack("<I", off)
        off += len(blk)
    return header + keys + offsets + b"".join(blocks)


def op(typ: int, value: int) -> bytes:
    body = struct.pack("<BQ", typ, value)
    return body + struct.pack("<I", fnv1a32(body))


def fixtures() -> dict[str, bytes]:
    """name → hand-assembled bytes for every fixture."""
    out = {
        "empty.roaring": snapshot([]),
        "simple_array.roaring": snapshot([(0, SIMPLE_VALUES)]),
        "multi_container.roaring": snapshot([
            (0, list(range(10))),
            (1, BITMAP_LOWS),
            (HIGH_KEY, [123]),
        ]),
    }
    # Runs-format fixtures: a pure run container, a mixed snapshot
    # (run + array + bitmap under one runs cookie), and a runs
    # snapshot with a trailing op-log that replays against the run
    # containers (interval split/extend on load).
    out["runs.roaring"] = snapshot_runs([(0, RUN_VALUES, True)])
    out["runs_mixed.roaring"] = snapshot_runs([
        (0, ARRAY_VALUES, False),               # array block (not runny)
        (1, RUN_VALUES, True),                  # run block
        (2, BITMAP_LOWS, False),                # bitmap block
        (HIGH_KEY, [7, 8, 9, 10, 500], True),   # run block, 48-bit key
    ])
    out["runs_oplog.roaring"] = (
        out["runs.roaring"] + b"".join(op(t, v) for t, v in RUN_OPS))
    # Snapshot + appended op log (the on-disk WAL form a fragment file
    # has between snapshots, fragment.go:179-234).
    out["with_oplog.roaring"] = (
        out["simple_array.roaring"] + b"".join(op(t, v) for t, v in OPS))
    # The same logical bitmap in canonical snapshot form (what a
    # post-replay re-serialization must produce).
    replayed = sorted({v for v in SIMPLE_VALUES if v != 100}
                      | {5, 42, 2 * 65536 + 7})
    by_key: dict[int, list[int]] = {}
    for v in replayed:
        by_key.setdefault(v >> 16, []).append(v & 0xFFFF)
    out["with_oplog.expected.roaring"] = snapshot(sorted(by_key.items()))
    return out


# Fixture bit sets, kept in sync with tests/test_golden.py.
SIMPLE_VALUES = [1, 5, 100, 65535]
BITMAP_LOWS = list(range(0, 10000, 2))       # 5000 values → bitmap kind
HIGH_KEY = 1 << 21                           # a 48-bit container key
OPS = [(0, 2 * 65536 + 7), (0, 5), (1, 100), (0, 42)]  # add/add/rm/add
# Three intervals (one past ARRAY_MAX long, so no legacy kind round-trips
# it as an array) + a lone value.
RUN_VALUES = (list(range(100, 5000)) + list(range(60000, 60010)) + [65535])
# Isolated values (every other) — optimize() must keep these an array
# (5 single-value runs would cost 22 bytes vs the 20-byte array block).
ARRAY_VALUES = [0, 2, 4, 6, 8]
# Replay against runs: extend a run edge, split a run, add a new
# container, remove a lone value (run deletion).
RUN_OPS = [(0, 5000), (1, 2000), (0, 3 * 65536 + 9), (1, 65535)]


def main(out_dir: str = HERE) -> None:
    for name, data in fixtures().items():
        with open(os.path.join(out_dir, name), "wb") as f:
            f.write(data)
        print(f"{name}: {len(data)} bytes")


if __name__ == "__main__":
    import sys
    main(sys.argv[1] if len(sys.argv) > 1 else HERE)
