"""Query lifecycle subsystem tests (pilosa_tpu.sched): admission
control, deadlines + budgets, cancellation + visibility, ownership-
gated fast paths, and the client's deadline-honoring retry loop."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.cluster.broadcast import CancelQueryMessage
from pilosa_tpu.cluster.topology import new_cluster
from pilosa_tpu.errors import QueryCancelledError, QueryDeadlineError
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.sched import (AdmissionController, AdmissionFullError,
                              QueryContext, QueryRegistry)
from pilosa_tpu.sched import context as sched_context
from pilosa_tpu.server.server import Server
from pilosa_tpu.utils.config import QueryConfig


# ---------------------------------------------------------------------------
# QueryContext


class TestQueryContext:
    def test_no_deadline_never_expires(self):
        ctx = QueryContext(pql="Count()")
        assert ctx.remaining() is None
        assert not ctx.expired()
        ctx.check()  # no raise

    def test_deadline_expiry(self):
        ctx = QueryContext(timeout_s=0.02)
        assert 0 < ctx.remaining() <= 0.02
        ctx.check()
        time.sleep(0.03)
        assert ctx.expired()
        with pytest.raises(QueryDeadlineError, match=ctx.id):
            ctx.check()
        assert ctx.state == "expired"

    def test_cancel(self):
        ctx = QueryContext()
        ctx.cancel("operator said so")
        with pytest.raises(QueryCancelledError, match="operator"):
            ctx.check()
        assert ctx.state == "cancelled"

    def test_stage_timings_and_json(self):
        ctx = QueryContext(pql="Bitmap(rowID=1)", index="i",
                           lane="read", timeout_s=30)
        with ctx.stage("execute"):
            time.sleep(0.01)
        ctx.add_leg("peer:10101", 7)
        j = ctx.to_json()
        assert j["index"] == "i" and j["lane"] == "read"
        assert j["stages"]["execute"] >= 0.01
        assert j["legs"] == [{"host": "peer:10101", "slices": 7}]
        assert 0 < j["remainingS"] <= 30

    def test_thread_local_propagation(self):
        ctx = QueryContext()
        assert sched_context.current() is None
        with sched_context.use(ctx):
            assert sched_context.current() is ctx
            ctx.cancel()
            with pytest.raises(QueryCancelledError):
                sched_context.check_current()
        assert sched_context.current() is None
        sched_context.check_current()  # unbound: no raise


# ---------------------------------------------------------------------------
# AdmissionController


class TestAdmission:
    def test_cap_and_release(self):
        ac = AdmissionController(concurrency=2, queue_depth=4)
        s1, s2 = ac.acquire("read"), ac.acquire("read")
        assert ac.in_flight == 2
        s1.release()
        s1.release()  # idempotent
        assert ac.in_flight == 1
        s2.release()
        assert ac.in_flight == 0

    def test_full_queue_rejects_with_retry_after(self):
        ac = AdmissionController(concurrency=1, queue_depth=0)
        slot = ac.acquire("read")
        with pytest.raises(AdmissionFullError) as ei:
            ac.acquire("read")
        assert ei.value.retry_after_s >= 1
        assert ac.snapshot()["rejected"] == 1
        slot.release()
        ac.acquire("read").release()  # capacity came back

    def test_waiter_gets_slot_on_release(self):
        ac = AdmissionController(concurrency=1, queue_depth=2)
        slot = ac.acquire("read")
        got = []

        def waiter():
            with ac.acquire("read"):
                got.append(True)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        assert not got  # queued behind the held slot
        slot.release()
        t.join(timeout=5)
        assert got and ac.in_flight == 0

    def test_deadline_expires_while_queued(self):
        ac = AdmissionController(concurrency=1, queue_depth=2)
        slot = ac.acquire("read")
        ctx = QueryContext(timeout_s=0.1)
        t0 = time.monotonic()
        with pytest.raises(QueryDeadlineError):
            ac.acquire("read", ctx)
        assert time.monotonic() - t0 < 2
        # The dead waiter left the queue; the slot is intact.
        assert ac.snapshot()["queued"] == {}
        slot.release()
        assert ac.in_flight == 0

    def test_cancel_while_queued(self):
        ac = AdmissionController(concurrency=1, queue_depth=2)
        slot = ac.acquire("read")
        ctx = QueryContext()
        threading.Timer(0.05, ctx.cancel).start()
        with pytest.raises(QueryCancelledError):
            ac.acquire("read", ctx)
        slot.release()
        assert ac.in_flight == 0

    def test_weighted_lanes_share_under_contention(self):
        """A write burst must not starve the admin lane: with
        weights read:4/write:2/admin:1 and one slot, queued admin work
        is granted interleaved with writes, not after all of them."""
        ac = AdmissionController(concurrency=1, queue_depth=16)
        gate = ac.acquire("read")
        order = []
        mu = threading.Lock()

        def worker(lane):
            with ac.acquire(lane):
                with mu:
                    order.append(lane)

        threads = []
        for _ in range(6):
            threads.append(threading.Thread(target=worker,
                                            args=("write",)))
        threads.append(threading.Thread(target=worker, args=("admin",)))
        for t in threads:
            t.start()
            time.sleep(0.02)  # deterministic FIFO arrival
        time.sleep(0.1)
        gate.release()
        for t in threads:
            t.join(timeout=10)
        # Stride scheduling: admin (weight 1) lands before the write
        # backlog fully drains (pure FIFO would put it last).
        assert order.index("admin") < len(order) - 1
        assert ac.in_flight == 0


# ---------------------------------------------------------------------------
# QueryRegistry


class TestRegistry:
    def test_track_and_active(self):
        reg = QueryRegistry()
        ctx = QueryContext(pql="Count(Bitmap(rowID=1))", index="i")
        with reg.track(ctx):
            assert len(reg) == 1
            assert reg.active()[0]["id"] == ctx.id
            assert reg.get(ctx.id) is ctx
        assert len(reg) == 0 and ctx.state == "done"

    def test_finish_records_error_state(self):
        reg = QueryRegistry()
        ctx = QueryContext()
        with pytest.raises(RuntimeError):
            with reg.track(ctx):
                raise RuntimeError("boom")
        assert ctx.state == "error" and len(reg) == 0

    def test_cancel_local_cancels_whole_id_group(self):
        reg = QueryRegistry()
        a = QueryContext(id="q1")
        b = QueryContext(id="q1")  # a leg registered under the same id
        reg.register(a)
        reg.register(b)
        assert reg.cancel_local("q1") == 2
        assert a.cancelled() and b.cancelled()
        assert reg.cancel_local("missing") == 0

    def test_slow_query_log(self):
        reg = QueryRegistry(slow_threshold_s=0.01)
        ctx = QueryContext(pql="TopN(frame=f, n=10)", index="i")
        with reg.track(ctx), ctx.stage("execute"):
            time.sleep(0.02)
        slow = reg.slow_queries()
        assert len(slow) == 1
        assert slow[0]["pql"] == "TopN(frame=f, n=10)"
        assert slow[0]["elapsedS"] >= 0.01
        assert "execute" in slow[0]["stages"]

    def test_fast_queries_stay_out_of_slow_log(self):
        reg = QueryRegistry(slow_threshold_s=10)
        with reg.track(QueryContext()):
            pass
        assert reg.slow_queries() == []


# ---------------------------------------------------------------------------
# Client: deadline-budget socket timeouts + retry loop


class _BlackHole:
    """Accepts TCP connections and never responds — a stalled peer."""

    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.host = "127.0.0.1:%d" % self.sock.getsockname()[1]

    def close(self):
        self.sock.close()


class TestClientDeadline:
    def test_stalled_peer_surfaces_deadline_not_double_timeout(self):
        """The attempt's socket timeout is clamped to the remaining
        budget, and the idempotent retry must NOT start once the
        budget is gone — total elapsed ≈ the budget, not N × the
        30s default client timeout."""
        from pilosa_tpu.cluster.client import Client
        hole = _BlackHole()
        try:
            client = Client(hole.host, timeout=30.0)
            t0 = time.monotonic()
            with pytest.raises(QueryDeadlineError):
                client.execute_query(None, "i", "Count(Bitmap(rowID=1))",
                                     deadline_s=0.4)
            elapsed = time.monotonic() - t0
            assert elapsed < 3, elapsed  # nowhere near 30s or 60s
        finally:
            hole.close()

    def test_exhausted_budget_never_starts_an_attempt(self):
        from pilosa_tpu.cluster.client import Client
        hole = _BlackHole()
        try:
            client = Client(hole.host)
            t0 = time.monotonic()
            with pytest.raises(QueryDeadlineError):
                client._do("GET", "/version", deadline_s=-1.0)
            assert time.monotonic() - t0 < 0.5
        finally:
            hole.close()

    def test_no_deadline_keeps_plain_client_error(self):
        from pilosa_tpu.cluster.client import Client, ClientError
        client = Client("127.0.0.1:1", timeout=0.2)  # nothing listens
        with pytest.raises(ClientError):
            client.execute_query(None, "i", "Count(Bitmap(rowID=1))")

    def test_pooled_connection_timeout_restored_after_clamp(self):
        """A budget-clamped request must not leave its tiny socket
        timeout armed on the pooled connection — the next deadline-
        free request re-arms the default (review finding)."""
        from pilosa_tpu.cluster.client import Client
        delay = {"s": 0.0}
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        host = "127.0.0.1:%d" % srv.getsockname()[1]
        stop = threading.Event()

        def serve():
            while not stop.is_set():
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return
                while not stop.is_set():
                    try:
                        if not conn.recv(65536):
                            break
                    except OSError:
                        break
                    time.sleep(delay["s"])
                    conn.sendall(b"HTTP/1.1 200 OK\r\n"
                                 b"Content-Length: 2\r\n\r\n{}")
                conn.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        try:
            client = Client(host, timeout=30.0)
            # Fast request under a small budget: succeeds, and its
            # connection (armed at ~0.5s) returns to the pool.
            status, _ = client._do("GET", "/x", deadline_s=0.5)
            assert status == 200
            # Slow response on the SAME pooled connection with no
            # deadline: must succeed under the restored 30s default
            # (the leaked 0.5s timeout would raise mid-response).
            delay["s"] = 0.8
            status, _ = client._do("GET", "/x", idempotent=False)
            assert status == 200
        finally:
            stop.set()
            srv.close()

    def test_routing_client_propagates_lifecycle_kwargs(self):
        """The REAL server wiring (executor → _RoutingClient → pooled
        Client) must carry deadline_s/query_id — without the marker the
        whole fan-out propagation is dead code (review finding)."""
        from pilosa_tpu.server.server import _RoutingClient
        assert _RoutingClient.deadline_aware
        seen = {}

        class FakeClient:
            def execute_query(self, node, index, query, slices,
                              remote, pod_local=False, deadline_s=None,
                              query_id=None):
                seen.update(deadline_s=deadline_s, query_id=query_id)
                return []

        class FakeServer:
            def client_for(self, host):
                return FakeClient()

        rc = _RoutingClient(FakeServer())
        from pilosa_tpu.cluster.topology import Node
        rc.execute_query(Node("peer:1"), "i", "Count(Bitmap(rowID=1))",
                         None, remote=True, deadline_s=1.5,
                         query_id="q77")
        assert seen == {"deadline_s": 1.5, "query_id": "q77"}


# ---------------------------------------------------------------------------
# Ownership-gated fast paths (multi-node clusters keep device/host fast
# paths for locally-owned work)


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


class TestOwnershipGates:
    def _fill(self, holder, rows=3, slices=2):
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("general")
        for r in range(rows):
            for s in range(slices):
                f.set_bit("standard", r, s * SLICE_WIDTH + r)

    def test_owns_all_slices(self, holder):
        # replica_n == cluster size: every node owns every slice.
        full = Executor(holder, host="a",
                        cluster=new_cluster(["a", "b"], replica_n=2))
        assert full._owns_all_slices("i", list(range(16)))
        # replica_n=1 splits ownership: some slice lands only on b.
        split = Executor(holder, host="a",
                         cluster=new_cluster(["a", "b"], replica_n=1))
        assert not split._owns_all_slices("i", list(range(16)))
        single = Executor(holder, host="only",
                          cluster=new_cluster(["only"]))
        assert single._owns_all_slices("i", list(range(16)))

    def test_result_cache_engages_on_fully_replicated_cluster(self,
                                                              holder):
        self._fill(holder)
        from pilosa_tpu.pql.parser import parse
        ex = Executor(holder, host="a",
                      cluster=new_cluster(["a", "b"], replica_n=2))
        call = parse("Union(Bitmap(rowID=0), Bitmap(rowID=1))").calls[0]
        assert ex._bitmap_result_key("i", call, [0, 1]) is not None

    def test_result_cache_stays_off_on_split_ownership(self, holder):
        self._fill(holder)
        from pilosa_tpu.pql.parser import parse
        ex = Executor(holder, host="a",
                      cluster=new_cluster(["a", "b"], replica_n=1))
        call = parse("Union(Bitmap(rowID=0), Bitmap(rowID=1))").calls[0]
        assert ex._bitmap_result_key("i", call, list(range(4))) is None

    def test_single_pass_topn_engages_on_fully_replicated_cluster(
            self, holder):
        self._fill(holder, rows=5, slices=2)
        from pilosa_tpu.pql.parser import parse
        ex = Executor(holder, host="a",
                      cluster=new_cluster(["a", "b"], replica_n=2))
        call = parse('TopN(frame="general", n=3)').calls[0]
        fast = ex._topn_host_single_pass("i", call, [0, 1],
                                         ExecOptions())
        assert fast is not None
        # And it matches the general (fan-out) path's answer.
        general = ex._top_n_slices("i", call, [0, 1], ExecOptions())
        assert [(p.id, p.count) for p in fast[:3]] == \
            [(p.id, p.count) for p in general[:3]]


# ---------------------------------------------------------------------------
# In-process server: end-to-end lifecycle over real HTTP


def make_server(tmp_path, name="s", **qc):
    s = Server(str(tmp_path / name), host="127.0.0.1:0",
               anti_entropy_interval=0, polling_interval=0,
               query_config=QueryConfig(**qc))
    s.open()
    return s


def http_post(host, path, body=b"", headers=None):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST", headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, r.read(), dict(r.headers)


def http_get(host, path):
    with urllib.request.urlopen(f"http://{host}{path}", timeout=30) as r:
        return json.loads(r.read())


class _SlowExecutor:
    """Delegating wrapper that busy-waits (cooperatively checking the
    query context) — a stand-in for a genuinely long query."""

    def __init__(self, real, seconds=30.0):
        self._real = real
        self._seconds = seconds

    def __getattr__(self, name):
        return getattr(self._real, name)

    def execute(self, index, query, slices=None, opt=None, **kw):
        t0 = time.monotonic()
        while time.monotonic() - t0 < self._seconds:
            if opt is not None and opt.ctx is not None:
                opt.ctx.check()
            time.sleep(0.005)
        return self._real.execute(index, query, slices, opt, **kw)


class TestServerLifecycle:
    @pytest.fixture
    def server(self, tmp_path):
        s = make_server(tmp_path, concurrency=2, queue_depth=1,
                        slow_threshold=0.0)
        http_post(s.host, "/index/i")
        http_post(s.host, "/index/i/frame/f")
        http_post(s.host, "/index/i/query",
                  b'SetBit(frame="f", rowID=1, columnID=3)')
        yield s
        s.close()

    def test_query_id_header_and_debug_queries_empty(self, server):
        st, _, hdrs = http_post(server.host, "/index/i/query",
                                b'Bitmap(frame="f", rowID=1)')
        assert st == 200 and hdrs.get("X-Pilosa-Query-Id")
        dq = http_get(server.host, "/debug/queries")
        assert dq["queries"] == []
        assert dq["admission"]["inFlight"] == 0

    def test_timeout_param_returns_504_within_budget(self, server):
        server.handler.executor = _SlowExecutor(server.executor)
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as ei:
            http_post(server.host, "/index/i/query?timeout=200ms",
                      b'Bitmap(frame="f", rowID=1)')
        assert ei.value.code == 504
        assert time.monotonic() - t0 < 5
        assert b"deadline" in ei.value.read()

    def test_deadline_header_wins_and_propagates_form(self, server):
        server.handler.executor = _SlowExecutor(server.executor)
        with pytest.raises(urllib.error.HTTPError) as ei:
            http_post(server.host, "/index/i/query",
                      b'Bitmap(frame="f", rowID=1)',
                      headers={"X-Pilosa-Deadline": "0.2"})
        assert ei.value.code == 504

    def test_saturation_answers_429_with_retry_after(self, server):
        server.handler.executor = _SlowExecutor(server.executor)
        threads = [threading.Thread(
            target=lambda: self._swallow(server, "timeout=3s"))
            for _ in range(3)]  # 2 slots + 1 queue seat
        for t in threads:
            t.start()
        time.sleep(0.4)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(server.host, "/index/i/query",
                          b'Bitmap(frame="f", rowID=1)')
            assert ei.value.code == 429
            assert int(ei.value.headers["Retry-After"]) >= 1
        finally:
            for ctx in [server.query_registry.get(q["id"])
                        for q in server.query_registry.active()]:
                if ctx is not None:
                    ctx.cancel()
            for t in threads:
                t.join(timeout=10)

    @staticmethod
    def _swallow(server, qs=""):
        try:
            http_post(server.host, f"/index/i/query?{qs}",
                      b'Bitmap(frame="f", rowID=1)')
        except urllib.error.HTTPError:
            pass

    def test_debug_queries_lists_in_flight_and_delete_cancels(
            self, server):
        server.handler.executor = _SlowExecutor(server.executor)
        res = {}

        def bg():
            try:
                http_post(server.host, "/index/i/query",
                          b'Count(Bitmap(frame="f", rowID=1))')
            except urllib.error.HTTPError as e:
                res["code"] = e.code
                res["body"] = e.read()

        t = threading.Thread(target=bg)
        t.start()
        deadline = time.monotonic() + 5
        qs = []
        while time.monotonic() < deadline and not qs:
            qs = http_get(server.host, "/debug/queries")["queries"]
            time.sleep(0.02)
        assert qs, "query never appeared in /debug/queries"
        q = qs[0]
        assert q["pql"].startswith("Count(") and q["state"] == "running"
        assert q["index"] == "i" and q["lane"] == "read"
        req = urllib.request.Request(
            f"http://{server.host}/debug/queries/{q['id']}",
            method="DELETE")
        out = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert out == {"id": q["id"], "cancelled": 1}
        t.join(timeout=10)
        assert res["code"] == 409 and b"cancelled" in res["body"]
        assert http_get(server.host, "/debug/queries")["queries"] == []
        assert server.admission.in_flight == 0

    def test_queued_deadline_maps_to_504_not_400(self, server):
        """A deadline expiring while the query WAITS in admission must
        surface as 504, same as any other expiry (review finding: the
        generic PilosaError catch used to turn it into a 400)."""
        server.handler.executor = _SlowExecutor(server.executor)
        # Fill both slots (cap 2) with long-deadline queries.
        threads = [threading.Thread(
            target=lambda: self._swallow(server, "timeout=5s"))
            for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.4)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(server.host, "/index/i/query?timeout=300ms",
                          b'Bitmap(frame="f", rowID=1)')
            assert ei.value.code == 504
        finally:
            for q in server.query_registry.active():
                server.query_registry.cancel_local(q["id"])
            for t in threads:
                t.join(timeout=10)
        assert server.admission.in_flight == 0

    def test_queued_query_visible_and_cancellable(self, server):
        """Queries waiting in admission appear at /debug/queries (state
        'queued') and DELETE cancels them out of the queue → 409
        (review finding: they used to register only after admission)."""
        server.handler.executor = _SlowExecutor(server.executor)
        runners = [threading.Thread(
            target=lambda: self._swallow(server, "timeout=5s"))
            for _ in range(2)]
        for t in runners:
            t.start()
        time.sleep(0.4)
        res = {}

        def queued():
            try:
                http_post(server.host, "/index/i/query",
                          b'Count(Bitmap(frame="f", rowID=9))')
            except urllib.error.HTTPError as e:
                res["code"] = e.code

        q = threading.Thread(target=queued)
        q.start()
        try:
            deadline = time.monotonic() + 5
            waiting = []
            while time.monotonic() < deadline and not waiting:
                waiting = [x for x in http_get(
                    server.host, "/debug/queries")["queries"]
                    if x["state"] == "queued"]
                time.sleep(0.02)
            assert waiting, "queued query never became visible"
            req = urllib.request.Request(
                f"http://{server.host}/debug/queries/"
                f"{waiting[0]['id']}", method="DELETE")
            urllib.request.urlopen(req, timeout=10).read()
            q.join(timeout=10)
            assert res["code"] == 409
        finally:
            for x in server.query_registry.active():
                server.query_registry.cancel_local(x["id"])
            for t in runners:
                t.join(timeout=10)
            q.join(timeout=10)
        assert server.admission.in_flight == 0
        assert len(server.query_registry) == 0

    def test_delete_unknown_query_is_noop(self, server):
        req = urllib.request.Request(
            f"http://{server.host}/debug/queries/deadbeef",
            method="DELETE")
        out = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert out["cancelled"] == 0

    def test_slow_query_log_through_http(self, tmp_path):
        s = make_server(tmp_path, "slow", slow_threshold=0.01)
        try:
            http_post(s.host, "/index/i")
            http_post(s.host, "/index/i/frame/f")
            s.handler.executor = _SlowExecutor(s.executor, seconds=0.05)
            http_post(s.host, "/index/i/query",
                      b'Bitmap(frame="f", rowID=1)')
            slow = http_get(s.host, "/debug/queries")["slow"]
            assert len(slow) == 1
            assert slow[0]["pql"] == 'Bitmap(frame="f", rowID=1)'
            assert "execute" in slow[0]["stages"]
        finally:
            s.close()

    def test_receive_message_cancels_registered_query(self, server):
        """The cluster-wide cancel path: a CancelQueryMessage arriving
        through the broadcast plane cancels the local legs."""
        ctx = QueryContext(id="abc123", pql="Count()")
        server.query_registry.register(ctx)
        try:
            server.receive_message(CancelQueryMessage("abc123"))
            assert ctx.cancelled()
        finally:
            server.query_registry.finish(ctx)

    def test_delete_broadcasts_cancel(self, server):
        sent = []

        class Spy:
            def send_async(self, m):
                sent.append(m)

            send_sync = send_async

        server.handler.broadcaster = Spy()
        req = urllib.request.Request(
            f"http://{server.host}/debug/queries/xyz", method="DELETE")
        urllib.request.urlopen(req, timeout=10).read()
        assert len(sent) == 1 and isinstance(sent[0],
                                             CancelQueryMessage)
        assert sent[0].id == "xyz"
        # ?local=true suppresses the re-broadcast (the form the
        # receive path uses, avoiding loops).
        req = urllib.request.Request(
            f"http://{server.host}/debug/queries/xyz?local=true",
            method="DELETE")
        urllib.request.urlopen(req, timeout=10).read()
        assert len(sent) == 1


class TestDeadlineStorm:
    def test_staggered_expiries_free_every_slot(self, tmp_path):
        """N concurrent queries with staggered deadlines against a
        slow executor: every one expires (504), every expiry frees its
        executor slot and registry entry — none leak."""
        s = make_server(tmp_path, "storm", concurrency=4,
                        queue_depth=16)
        try:
            http_post(s.host, "/index/i")
            http_post(s.host, "/index/i/frame/f")
            s.handler.executor = _SlowExecutor(s.executor)
            codes = []
            mu = threading.Lock()

            def one(timeout_ms):
                try:
                    http_post(s.host,
                              f"/index/i/query?timeout={timeout_ms}ms",
                              b'Count(Bitmap(frame="f", rowID=1))')
                    code = 200
                except urllib.error.HTTPError as e:
                    code = e.code
                with mu:
                    codes.append(code)

            threads = [threading.Thread(target=one,
                                        args=(50 + 25 * k,))
                       for k in range(12)]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert time.monotonic() - t0 < 20
            assert len(codes) == 12
            assert all(c == 504 for c in codes), codes
            # Nothing leaked: no slots held, no registry entries.
            assert s.admission.in_flight == 0
            assert len(s.query_registry) == 0
            snap = s.admission.snapshot()
            assert snap["queued"] == {}
        finally:
            s.close()


class TestQueryConfig:
    def test_sub_second_durations_round_trip(self, tmp_path):
        """to_toml must not truncate 0.5s → "0s" (= disabled) for the
        [query] durations (review finding)."""
        from pilosa_tpu.utils import config as config_mod
        cfg = config_mod.Config()
        cfg.query.default_timeout = 0.5
        cfg.query.slow_threshold = 0.25
        cfg.query.concurrency = 3
        path = tmp_path / "cfg.toml"
        path.write_text(cfg.to_toml())
        if config_mod.tomllib is None:
            pytest.skip("no TOML parser on this interpreter")
        got = config_mod.load(str(path), env={})
        assert got.query.default_timeout == 0.5
        assert got.query.slow_threshold == 0.25
        assert got.query.concurrency == 3

    def test_env_overrides(self):
        from pilosa_tpu.utils import config as config_mod
        cfg = config_mod.load(env={
            "PILOSA_QUERY_CONCURRENCY": "7",
            "PILOSA_QUERY_QUEUE_DEPTH": "9",
            "PILOSA_QUERY_DEFAULT_TIMEOUT": "2s",
            "PILOSA_QUERY_SLOW_THRESHOLD": "150ms"})
        assert cfg.query.concurrency == 7
        assert cfg.query.queue_depth == 9
        assert cfg.query.default_timeout == 2.0
        assert cfg.query.slow_threshold == 0.15


class TestWarmup:
    def test_warmup_compiles_and_reports_done(self, tmp_path,
                                              monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_WARMUP", "1")
        s = Server(str(tmp_path / "warm"), host="127.0.0.1:0",
                   anti_entropy_interval=0, polling_interval=0)
        s.open()
        try:
            assert s.warmup is not None
            s.warmup.wait(timeout=120)
            status = http_get(s.host, "/status")
            assert status["warmup"]["state"] == "done", status["warmup"]
            from pilosa_tpu.parallel import programs
            assert set(status["warmup"]["compiled"]) == set(
                programs.CATALOGUE)
            cov = status["warmup"]["coverage"]
            assert cov["warmed"] == cov["programs"] == len(
                programs.CATALOGUE)
            assert cov["missing"] == []
            # An empty holder warms at the minimum bucket (= the
            # device count); real servers key it off max_slice.
            assert status["warmup"]["bucket"] >= 1
        finally:
            s.close()

    def test_warmup_absent_when_disabled(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_WARMUP", "0")
        s = Server(str(tmp_path / "cold"), host="127.0.0.1:0",
                   anti_entropy_interval=0, polling_interval=0)
        s.open()
        try:
            assert s.warmup is None
            assert "warmup" not in http_get(s.host, "/status")
        finally:
            s.close()
