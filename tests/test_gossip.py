"""Gossip membership backend tests.

Models the reference's gossip integration (gossip/gossip.go): join via
seed push/pull, sync broadcast over TCP, async broadcast via piggybacked
gossip, full-state status merge, and SWIM failure detection.
"""

from __future__ import annotations

import time

import pytest

from pilosa_tpu.cluster.gossip import GossipNodeSet
from pilosa_tpu.proto import internal_pb2 as pb


class RecordingHandler:
    """BroadcastHandler + StatusHandler double."""

    def __init__(self, host: str):
        self.host = host
        self.messages = []
        self.remote_statuses = []

    def receive_message(self, m) -> None:
        self.messages.append(m)

    def local_status(self) -> dict:
        return {"host": self.host, "indexes": [{"name": "i0",
                                                "maxSlice": 3,
                                                "frames": []}]}

    def handle_remote_status(self, status: dict) -> None:
        self.remote_statuses.append(status)


def make_node(host: str, seeds=None, **kw) -> tuple[GossipNodeSet,
                                                    RecordingHandler]:
    ns = GossipNodeSet(host, gossip_host="127.0.0.1:0", seeds=seeds or [],
                       probe_interval=0.1, probe_timeout=0.2,
                       push_pull_interval=0.3, **kw)
    h = RecordingHandler(host)
    ns.start(h)
    ns.open()
    return ns, h


def wait_until(cond, timeout: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def pair():
    a, ha = make_node("hostA:10101")
    b, hb = make_node("hostB:10101", seeds=[a.gossip_host])
    yield (a, ha, b, hb)
    a.close()
    b.close()


def test_join_via_seed(pair):
    a, _, b, _ = pair
    assert wait_until(lambda: len(a.nodes()) == 2)
    assert wait_until(lambda: len(b.nodes()) == 2)
    assert [n.host for n in a.nodes()] == ["hostA:10101", "hostB:10101"]


def test_push_pull_merges_status(pair):
    a, ha, b, hb = pair
    # The join push/pull already exchanged NodeStatus both ways.
    assert wait_until(lambda: any(
        s.get("host") == "hostB:10101" for s in ha.remote_statuses))
    assert wait_until(lambda: any(
        s.get("host") == "hostA:10101" for s in hb.remote_statuses))


def test_send_sync_delivers_to_peers(pair):
    a, _, b, hb = pair
    assert wait_until(lambda: len(a.nodes()) == 2)
    a.send_sync(pb.CreateIndexMessage(Index="syncidx"))
    assert wait_until(lambda: any(
        isinstance(m, pb.CreateIndexMessage) and m.Index == "syncidx"
        for m in hb.messages))


def test_send_async_gossips(pair):
    a, _, b, hb = pair
    assert wait_until(lambda: len(a.nodes()) == 2)
    a.send_async(pb.CreateSliceMessage(Index="gossipidx", Slice=7))
    # Rides piggyback on the periodic probe pings.
    assert wait_until(lambda: any(
        isinstance(m, pb.CreateSliceMessage) and m.Index == "gossipidx"
        and m.Slice == 7 for m in hb.messages))


def test_gossip_rumor_delivered_once_per_send(pair):
    # One async send is delivered exactly once despite riding many
    # piggyback rounds; a REPEATED send of identical bytes (e.g. create →
    # delete → create again) is a new rumor and must be delivered again.
    a, _, b, hb = pair
    assert wait_until(lambda: len(a.nodes()) == 2)

    def dups():
        return [m for m in hb.messages if getattr(m, "Index", "") == "dup"]

    a.send_async(pb.CreateIndexMessage(Index="dup"))
    assert wait_until(lambda: len(dups()) == 1)
    time.sleep(0.5)
    assert len(dups()) == 1

    a.send_async(pb.CreateIndexMessage(Index="dup"))  # same envelope bytes
    assert wait_until(lambda: len(dups()) == 2)
    time.sleep(0.5)
    assert len(dups()) == 2


def test_three_node_transitive_membership():
    a, _ = make_node("hostA:10101")
    b, _ = make_node("hostB:10101", seeds=[a.gossip_host])
    c, _ = make_node("hostC:10101", seeds=[a.gossip_host])
    try:
        # C learns about B (and vice versa) through A's state.
        assert wait_until(lambda: len(a.nodes()) == 3)
        assert wait_until(lambda: len(b.nodes()) == 3)
        assert wait_until(lambda: len(c.nodes()) == 3)
    finally:
        a.close()
        b.close()
        c.close()


def test_failure_detection_marks_dead():
    a, _ = make_node("hostA:10101", suspect_after=2)
    b, _ = make_node("hostB:10101", seeds=[a.gossip_host], suspect_after=2)
    try:
        assert wait_until(lambda: len(a.nodes()) == 2)
        b.close()
        assert wait_until(
            lambda: [n.host for n in a.nodes()] == ["hostA:10101"],
            timeout=10.0)
    finally:
        a.close()


def test_nodes_excludes_nothing_when_alone():
    a, _ = make_node("solo:10101")
    try:
        assert [n.host for n in a.nodes()] == ["solo:10101"]
    finally:
        a.close()
