"""Gossip membership backend tests.

Models the reference's gossip integration (gossip/gossip.go): join via
seed push/pull, sync broadcast over TCP, async broadcast via piggybacked
gossip, full-state status merge, and SWIM failure detection.
"""

from __future__ import annotations

import time

import pytest

from pilosa_tpu.cluster.gossip import GossipNodeSet
from pilosa_tpu.proto import internal_pb2 as pb


class RecordingHandler:
    """BroadcastHandler + StatusHandler double."""

    def __init__(self, host: str):
        self.host = host
        self.messages = []
        self.remote_statuses = []

    def receive_message(self, m) -> None:
        self.messages.append(m)

    def local_status(self) -> pb.NodeStatus:
        # The wire type the reference's push/pull carries
        # (internal/private.proto:74-90, gossip.go:193-205).
        return pb.NodeStatus(Host=self.host, State="UP", Indexes=[
            pb.Index(Name="i0", MaxSlice=3, Slices=[0, 2])])

    def handle_remote_status(self, status: pb.NodeStatus) -> None:
        self.remote_statuses.append(status)


def make_node(host: str, seeds=None, **kw) -> tuple[GossipNodeSet,
                                                    RecordingHandler]:
    ns = GossipNodeSet(host, gossip_host="127.0.0.1:0", seeds=seeds or [],
                       probe_interval=0.1, probe_timeout=0.2,
                       push_pull_interval=0.3, **kw)
    h = RecordingHandler(host)
    ns.start(h)
    ns.open()
    return ns, h


def wait_until(cond, timeout: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def pair():
    a, ha = make_node("hostA:10101")
    b, hb = make_node("hostB:10101", seeds=[a.gossip_host])
    yield (a, ha, b, hb)
    a.close()
    b.close()


def test_join_via_seed(pair):
    a, _, b, _ = pair
    assert wait_until(lambda: len(a.nodes()) == 2)
    assert wait_until(lambda: len(b.nodes()) == 2)
    assert [n.host for n in a.nodes()] == ["hostA:10101", "hostB:10101"]


def test_push_pull_merges_status(pair):
    a, ha, b, hb = pair
    # The join push/pull already exchanged protobuf NodeStatus both ways,
    # including schema + owned slices.
    assert wait_until(lambda: any(
        s.Host == "hostB:10101" for s in ha.remote_statuses))
    assert wait_until(lambda: any(
        s.Host == "hostA:10101" for s in hb.remote_statuses))
    ns = next(s for s in ha.remote_statuses if s.Host == "hostB:10101")
    assert [(ix.Name, ix.MaxSlice, list(ix.Slices))
            for ix in ns.Indexes] == [("i0", 3, [0, 2])]


def test_send_sync_delivers_to_peers(pair):
    a, _, b, hb = pair
    assert wait_until(lambda: len(a.nodes()) == 2)
    a.send_sync(pb.CreateIndexMessage(Index="syncidx"))
    assert wait_until(lambda: any(
        isinstance(m, pb.CreateIndexMessage) and m.Index == "syncidx"
        for m in hb.messages))


def test_send_async_gossips(pair):
    a, _, b, hb = pair
    assert wait_until(lambda: len(a.nodes()) == 2)
    a.send_async(pb.CreateSliceMessage(Index="gossipidx", Slice=7))
    # Rides piggyback on the periodic probe pings.
    assert wait_until(lambda: any(
        isinstance(m, pb.CreateSliceMessage) and m.Index == "gossipidx"
        and m.Slice == 7 for m in hb.messages))


def test_gossip_rumor_delivered_once_per_send(pair):
    # One async send is delivered exactly once despite riding many
    # piggyback rounds; a REPEATED send of identical bytes (e.g. create →
    # delete → create again) is a new rumor and must be delivered again.
    a, _, b, hb = pair
    assert wait_until(lambda: len(a.nodes()) == 2)

    def dups():
        return [m for m in hb.messages if getattr(m, "Index", "") == "dup"]

    a.send_async(pb.CreateIndexMessage(Index="dup"))
    assert wait_until(lambda: len(dups()) == 1)
    time.sleep(0.5)
    assert len(dups()) == 1

    a.send_async(pb.CreateIndexMessage(Index="dup"))  # same envelope bytes
    assert wait_until(lambda: len(dups()) == 2)
    time.sleep(0.5)
    assert len(dups()) == 2


def test_three_node_transitive_membership():
    a, _ = make_node("hostA:10101")
    b, _ = make_node("hostB:10101", seeds=[a.gossip_host])
    c, _ = make_node("hostC:10101", seeds=[a.gossip_host])
    try:
        # C learns about B (and vice versa) through A's state.
        assert wait_until(lambda: len(a.nodes()) == 3)
        assert wait_until(lambda: len(b.nodes()) == 3)
        assert wait_until(lambda: len(c.nodes()) == 3)
    finally:
        a.close()
        b.close()
        c.close()


def test_failure_detection_marks_dead():
    a, _ = make_node("hostA:10101", suspect_after=2)
    b, _ = make_node("hostB:10101", seeds=[a.gossip_host], suspect_after=2)
    try:
        assert wait_until(lambda: len(a.nodes()) == 2)
        b.close()
        assert wait_until(
            lambda: [n.host for n in a.nodes()] == ["hostA:10101"],
            timeout=10.0)
    finally:
        a.close()


def test_nodes_excludes_nothing_when_alone():
    a, _ = make_node("solo:10101")
    try:
        assert [n.host for n in a.nodes()] == ["solo:10101"]
    finally:
        a.close()


def test_refutation_after_false_death(pair):
    """A false dead rumor about a live node is refuted: the victim hears
    it is presumed dead (via push/pull), re-announces alive with a higher
    incarnation, and the accuser flips it back (SWIM refutation)."""
    from pilosa_tpu.cluster.gossip import Member, STATE_DEAD
    a, _, b, _ = pair
    assert wait_until(lambda: len(a.nodes()) == 2)
    # Inject the false rumor into A: B is dead at B's current incarnation.
    inc = a._member_snapshot("hostB:10101").incarnation
    a._merge_member(Member("hostB:10101", b.gossip_host, inc, STATE_DEAD))
    # The merge took effect (B dead at A) — unless B's refutation
    # already landed: _gossip_update notifies the rumor's subject
    # directly (round 5), so the dead window can be sub-millisecond.
    assert ([n.host for n in a.nodes()] == ["hostA:10101"]
            or a._member_snapshot("hostB:10101").incarnation > inc)
    # The dead rumor reaches B (direct notify, else push/pull), which
    # refutes with incarnation inc+1; A must resurrect B.
    assert wait_until(lambda: len(a.nodes()) == 2, timeout=10.0)
    assert wait_until(
        lambda: a._member_snapshot("hostB:10101").incarnation > inc,
        timeout=10.0)


def test_dead_node_revival_after_partition_heal():
    """A node that really died and was marked dead rejoins (same name,
    fresh process): the join push/pull tells it the cluster believes it
    dead, it refutes, and membership heals to 2 alive."""
    a, _ = make_node("hostA:10101", suspect_after=2)
    b, _ = make_node("hostB:10101", seeds=[a.gossip_host], suspect_after=2)
    try:
        assert wait_until(lambda: len(a.nodes()) == 2)
        b.close()  # partition / crash
        assert wait_until(
            lambda: [n.host for n in a.nodes()] == ["hostA:10101"],
            timeout=10.0)
        # Heal: restart B under the same cluster identity.
        b2, _ = make_node("hostB:10101", seeds=[a.gossip_host],
                          suspect_after=2)
        try:
            assert wait_until(lambda: len(a.nodes()) == 2, timeout=10.0)
            assert wait_until(lambda: len(b2.nodes()) == 2, timeout=10.0)
        finally:
            b2.close()
    finally:
        a.close()


def test_asymmetric_direct_loss_does_not_kill():
    """SWIM indirect probes: with ONLY the direct A->B ping path cut,
    relay C still reaches B, so A must keep B alive indefinitely; when
    the indirect path is cut too, B becomes suspect and then dead."""
    from pilosa_tpu.cluster.gossip import STATE_ALIVE
    a, _ = make_node("hostA:10101", suspect_after=1,
                     suspect_timeout=0.6)
    b, _ = make_node("hostB:10101", seeds=[a.gossip_host],
                     suspect_after=1)
    c, _ = make_node("hostC:10101", seeds=[a.gossip_host],
                     suspect_after=1)
    try:
        assert wait_until(lambda: len(a.nodes()) == 3
                          and len(c.nodes()) == 3)
        # Cut ONLY A's direct pings to B (pingreq to C still flows,
        # C's relayed ping to B is its own socket — unaffected).
        orig_send = a._udp_send
        b_addr = b.gossip_host

        def lossy_send(addr, pkt, _orig=orig_send):
            if addr == b_addr and pkt.get("t") == "ping":
                return  # drop
            _orig(addr, pkt)

        a._udp_send = lossy_send
        # Many probe rounds at 0.1s cadence: B must stay a member of A's
        # view the whole time (indirect acks through C).
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            assert len(a.nodes()) == 3, "B was condemned despite relays"
            time.sleep(0.05)
        assert a._member_snapshot("hostB:10101").state == STATE_ALIVE
        # Sanity check of the whole suspect lifecycle: when B actually
        # dies (no process left to refute), A's suspicion must expire
        # into death — even with A's direct path still lossy.
        b.close()
        assert wait_until(
            lambda: "hostB:10101" not in [n.host for n in a.nodes()],
            timeout=10.0)
    finally:
        for ns in (a, b, c):
            ns.close()


def test_suspect_refuted_before_window_expires():
    """A suspect rumor reaching the victim is refuted with a bumped
    incarnation and the accuser returns it to alive (no death)."""
    from pilosa_tpu.cluster.gossip import Member, STATE_SUSPECT
    a, _ = make_node("hostA:10101", suspect_timeout=30.0)
    b, _ = make_node("hostB:10101", seeds=[a.gossip_host],
                     suspect_timeout=30.0)
    try:
        assert wait_until(lambda: len(a.nodes()) == 2)
        inc = a._member_snapshot("hostB:10101").incarnation
        a._merge_member(Member("hostB:10101", b.gossip_host, inc,
                               STATE_SUSPECT))
        # Still a member while suspect (memberlist semantics)...
        assert len(a.nodes()) == 2
        # ...and the refutation (via rumor/push-pull) bumps it back.
        assert wait_until(
            lambda: a._member_snapshot("hostB:10101").incarnation > inc,
            timeout=10.0)
        assert a._member_snapshot("hostB:10101").state == "alive"
    finally:
        a.close()
        b.close()


def test_hmac_rejects_spoofed_datagram():
    """With a shared key, an unauthenticated datagram must not poison
    membership; with a matching key the same packet is absorbed."""
    import json as json_mod
    import socket as socket_mod

    a, _ = make_node("hostA:10101", secret_key=b"k1")
    try:
        spoofed = {"t": "update", "from": "evil",
                   "updates": [{"name": "evil:10101",
                                "addr": "127.0.0.1:9", "inc": 5,
                                "state": "alive"}]}
        raw = json_mod.dumps(spoofed).encode()
        with socket_mod.socket(socket_mod.AF_INET,
                               socket_mod.SOCK_DGRAM) as s:
            from pilosa_tpu.cluster.gossip import _split_addr
            s.sendto(raw, _split_addr(a.gossip_host))
        time.sleep(0.5)
        assert [n.host for n in a.nodes()] == ["hostA:10101"]
        # The same bytes sealed with the right key DO get absorbed.
        sealed = a._seal(raw)
        with socket_mod.socket(socket_mod.AF_INET,
                               socket_mod.SOCK_DGRAM) as s:
            from pilosa_tpu.cluster.gossip import _split_addr
            s.sendto(sealed, _split_addr(a.gossip_host))
        assert wait_until(
            lambda: "evil:10101" in [n.host for n in a.nodes()],
            timeout=5.0)
    finally:
        a.close()


def test_hmac_cluster_converges_and_syncs():
    """Two nodes sharing a key join and exchange sync broadcasts
    (sealed TCP frames end-to-end)."""
    a, ha = make_node("hostA:10101", secret_key="swordfish")
    b, hb = make_node("hostB:10101", seeds=[a.gossip_host],
                      secret_key="swordfish")
    try:
        assert wait_until(lambda: len(a.nodes()) == 2
                          and len(b.nodes()) == 2)
        from pilosa_tpu.proto import internal_pb2 as pb
        a.send_sync(pb.CreateIndexMessage(Index="idx"))
        assert wait_until(lambda: any(
            getattr(m, "Index", "") == "idx" for m in hb.messages))
    finally:
        a.close()
        b.close()
