"""Distributed fast paths (ROADMAP item 3): mutation-generation
tokens, TopN pushdown, and the coordinator hot-query result cache.

Unit legs drive the executor against scripted transports (the
executor_test.go mock-server seam); the cluster leg runs a REAL 2-node
gossip cluster (replicas=1) plus a single-node reference server and
proves (a) distributed TopN merge is differentially equal to
single-node, (b) a write through any node invalidates the coordinator
result cache on the next query, and (c) failpoint-injected rpc.recv
failures degrade to the fan-out path — never a wrong answer."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402
from pilosa_tpu.cluster import generations as gens_mod  # noqa: E402
from pilosa_tpu.cluster.generations import GenerationMap  # noqa: E402
from pilosa_tpu.cluster.topology import new_cluster  # noqa: E402
from pilosa_tpu.errors import PilosaError  # noqa: E402
from pilosa_tpu.executor import ExecOptions, Executor  # noqa: E402
from pilosa_tpu.models.holder import Holder  # noqa: E402
from pilosa_tpu.obs import metrics as obs_metrics  # noqa: E402
from pilosa_tpu.pql.parser import parse as parse_pql  # noqa: E402
from pilosa_tpu.storage.bitmap import Bitmap  # noqa: E402
from pilosa_tpu.storage.cache import Pair  # noqa: E402


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def must_set(holder, index, frame, row, col, view="standard"):
    idx = holder.create_index_if_not_exists(index)
    f = idx.create_frame_if_not_exists(frame)
    f.set_bit(view, row, col)


# ---------------------------------------------------------------------------
# generations module: tokens, wire codec, GenerationMap


class TestGenerationsModule:
    def test_wire_round_trip(self):
        tokens = {0: {"f/standard": (3, 7)},
                  2: {"f/standard": (4, 0), "g/inverse": (5, 12)},
                  5: {}}
        payload = gens_mod.encode_wire("idx", tokens)
        got = gens_mod.decode_wire(payload)
        assert got is not None
        index, decoded = got
        assert index == "idx"
        assert decoded == tokens

    def test_wire_truncation_drops_whole_slices(self):
        tokens = {s: {f"f{i}/standard": (1, 1) for i in range(10)}
                  for s in range(5)}
        payload = gens_mod.encode_wire("i", tokens, max_fragments=25)
        data = json.loads(payload)
        assert data["x"] == 1
        # Whole slices only, ascending: the first two fit (20 frags).
        assert sorted(data["t"]) == ["0", "1"]
        for m in data["t"].values():
            assert len(m) == 10  # never a partial slice

    def test_wire_byte_budget_binds(self):
        """The encoded payload must stay under the byte budget even
        when the fragment cap would admit more — an over-64KiB header
        line fails the whole response carrying it."""
        tokens = {s: {f"frame{i:04d}/standard": (10 ** 9 + i, 10 ** 8)
                      for i in range(50)}
                  for s in range(100)}
        payload = gens_mod.encode_wire("i", tokens, max_bytes=4096)
        assert len(payload) <= 4096
        data = json.loads(payload)
        assert data["x"] == 1 and data["t"]  # some whole slices fit
        # Even a single oversized slice cannot blow the budget.
        one = {0: {f"f{i:05d}/standard": (i, i) for i in range(3000)}}
        payload = gens_mod.encode_wire("i", one, max_bytes=2048)
        assert len(payload) <= 2048
        assert json.loads(payload)["t"] == {}

    def test_decode_garbage_is_none(self):
        assert gens_mod.decode_wire("not json") is None
        assert gens_mod.decode_wire('{"t": {}}') is None  # no index
        assert gens_mod.decode_wire('[1,2]') is None

    def test_map_apply_token_and_staleness(self):
        m = GenerationMap(staleness_s=30.0)
        m.apply("peer:1", "i", {4: {"f/standard": (9, 2)}})
        assert m.token("peer:1", "i", "f", "standard", 4) == (9, 2)
        # Absent fragment in a KNOWN slice reads (0, 0) — distinct
        # from an unknown slice, which reads None.
        assert m.token("peer:1", "i", "g", "standard", 4) == (0, 0)
        assert m.token("peer:1", "i", "f", "standard", 5) is None
        assert m.token("peer:2", "i", "f", "standard", 4) is None
        # Staleness bound: a negative max-age forces every entry stale.
        assert m.token("peer:1", "i", "f", "standard", 4,
                       max_age_s=-1.0) is None

    def test_map_newest_min_ts_filter(self):
        m = GenerationMap()
        t0 = time.monotonic()
        m.apply("a:1", "i", {0: {"f/standard": (1, 1)}})
        got = m.newest("i", 0)
        assert got is not None and got[0] == "a:1"
        # An entry applied BEFORE min_ts is filtered out.
        assert m.newest("i", 0, min_ts=time.monotonic() + 1) is None
        assert m.newest("i", 0, min_ts=t0) is not None
        # A fresher peer wins.
        m.apply("b:1", "i", {0: {"f/standard": (2, 5)}})
        assert m.newest("i", 0)[0] == "b:1"

    def test_slice_tokens_from_holder(self, holder):
        must_set(holder, "i", "f", 1, 3)
        toks = gens_mod.slice_tokens(holder, "i", 0)
        assert "f/standard" in toks
        uid, gen = toks["f/standard"]
        holder.frame("i", "f").set_bit("standard", 1, 4)
        uid2, gen2 = gens_mod.slice_tokens(holder, "i",
                                           0)["f/standard"]
        assert uid2 == uid and gen2 > gen  # writes bump the token
        assert gens_mod.slice_tokens(holder, "i", 9) == {}
        assert gens_mod.slice_tokens(holder, "nope", 0) == {}


# ---------------------------------------------------------------------------
# remote-token result-residency keys (executor._bitmap_result_key)


class BitmapFakeClient:
    """Scripted remote transport answering bitmap legs."""

    generation_aware = True

    def __init__(self, fn):
        self.fn = fn
        self.calls = []

    def execute_query(self, node, index, query, slices, remote,
                      **kwargs):
        self.calls.append((node.host, index, query, slices, remote))
        return self.fn(node, index, query, slices)


class TestRemoteResultKey:
    def _setup(self, holder, fn=None):
        must_set(holder, "i", "general", 10, 3)
        must_set(holder, "i", "general", 11, 3)
        holder.index("i").set_remote_max_slice(2)
        cluster = new_cluster(["local", "remotehost"], replica_n=1)
        client = BitmapFakeClient(fn or (lambda *a: [Bitmap()]))
        gens = GenerationMap(staleness_s=60.0)
        e = Executor(holder, host="local", cluster=cluster,
                     client=client, gens=gens, use_mesh=False)
        remote_slices = [s for s in range(3)
                         if cluster.fragment_nodes("i", s)[0].host
                         == "remotehost"]
        assert remote_slices, "3 slices over 2 nodes: some are remote"
        return e, client, gens, remote_slices

    def _remote_tokens(self, remote_slices, gen=0):
        return {s: {"general/standard": (100 + s, gen)}
                for s in remote_slices}

    def test_key_requires_fresh_remote_tokens(self, holder):
        e, _client, gens, remote = self._setup(holder)
        call = parse_pql('Union(Bitmap(rowID=10, frame=general),'
                         ' Bitmap(rowID=11, frame=general))').calls[0]
        slices = [0, 1, 2]
        # Empty map: slices owned elsewhere are unkeyable.
        assert e._bitmap_result_key("i", call, slices) is None
        gens.apply("remotehost", "i", self._remote_tokens(remote))
        key1 = e._bitmap_result_key("i", call, slices)
        assert key1 is not None
        # A bumped remote generation changes the key (invalidation by
        # mismatch), and the peer host is part of the token (uids are
        # process-local).
        gens.apply("remotehost", "i",
                   self._remote_tokens(remote, gen=1))
        key2 = e._bitmap_result_key("i", call, slices)
        assert key2 is not None and key2 != key1
        assert any(t[0] == "remotehost" for t in key2[3])
        # Past the staleness bound the key disappears again.
        e._gen_staleness_s = -1.0
        assert e._bitmap_result_key("i", call, slices) is None

    def test_remote_result_caches_and_invalidates(self, holder):
        def fn(node, index, query, slices):
            bm = Bitmap()
            for s in slices:
                bm.set_bit(s * SLICE_WIDTH + 7)
            return [bm]

        e, client, gens, remote = self._setup(holder, fn)
        q = ('Union(Bitmap(rowID=10, frame=general),'
             ' Bitmap(rowID=11, frame=general))')
        gens.apply("remotehost", "i", self._remote_tokens(remote))
        r1 = e.execute("i", q)[0]
        n_calls = len(client.calls)
        assert n_calls >= 1
        # Same tokens: the repeat serves from residency — no remote leg.
        r2 = e.execute("i", q)[0]
        assert len(client.calls) == n_calls
        assert sorted(r2.bits()) == sorted(r1.bits())
        # A remote write (token bump) forces a recompute.
        gens.apply("remotehost", "i",
                   self._remote_tokens(remote, gen=3))
        e.execute("i", q)
        assert len(client.calls) > n_calls

    def test_env_configurable_bounds(self, holder, monkeypatch):
        monkeypatch.setenv("PILOSA_QUERY_RESULT_CACHE_ENTRIES", "2")
        monkeypatch.setenv("PILOSA_QUERY_RESULT_CACHE_BITS", "1024")
        monkeypatch.setenv("PILOSA_QUERY_CLUSTER_CACHE_ENTRIES", "5")
        monkeypatch.setenv("PILOSA_CLUSTER_GEN_STALENESS", "250ms")
        e = Executor(holder, host="local", use_mesh=False)
        assert e._result_cache_entries == 2
        assert e._result_cache_bits == 1024
        assert e._cluster_cache_entries == 5
        assert e._gen_staleness_s == 0.25
        e2 = Executor(holder, host="local", use_mesh=False,
                      result_cache_entries=9, result_cache_bits=99,
                      cluster_cache_entries=0, gen_staleness_s=1.5)
        assert (e2._result_cache_entries, e2._result_cache_bits,
                e2._cluster_cache_entries,
                e2._gen_staleness_s) == (9, 99, 0, 1.5)


# ---------------------------------------------------------------------------
# hedged reads: the WINNING leg's generation tokens only (regression)


class TestHedgedGenerations:
    def test_loser_tokens_never_poison_the_map(self, holder):
        """A slow primary that straggles in AFTER the hedge won must
        not land its (older) tokens in the coordinator map."""
        from pilosa_tpu.cluster.topology import Node

        must_set(holder, "i", "general", 1, 1)
        cluster = new_cluster(["slowpeer:1", "fastpeer:2"],
                              replica_n=2)
        gens = GenerationMap(staleness_s=60.0)
        released = []

        class HedgeClient:
            generation_aware = True

            def execute_query(self, node, index, query, slices,
                              remote, gens_out=None, **kwargs):
                payload = gens_mod.encode_wire(
                    index, {0: {"general/standard":
                                (1, 0 if "slow" in node.host else 5)}})
                if "slow" in node.host:
                    time.sleep(0.6)  # loses the race
                if gens_out is not None:
                    gens_out.append((node.host, payload))
                released.append(node.host)
                return [3]

        e = Executor(holder, host="coord", cluster=cluster,
                     client=HedgeClient(), gens=gens, use_mesh=False)
        c = parse_pql('Count(Bitmap(rowID=1, frame=general))').calls[0]
        res = e._exec_remote_hedged(
            Node("slowpeer:1"), "i", c, [0], ExecOptions(),
            map_fn=None, reduce_fn=lambda prev, v: (prev or 0) + v,
            hedge_s=0.05)
        assert res == 3
        # Winner (fast) tokens landed; loser's did not.
        assert gens.token("fastpeer:2", "i", "general", "standard",
                          0) == (1, 5)
        assert gens.token("slowpeer:1", "i", "general", "standard",
                          0) is None
        # Even after the loser finally completes, its tokens stay out.
        deadline = time.time() + 5
        while "slowpeer:1" not in released and time.time() < deadline:
            time.sleep(0.05)
        assert gens.token("slowpeer:1", "i", "general", "standard",
                          0) is None


# ---------------------------------------------------------------------------
# distributed TopN pushdown (unit, scripted transport)


class TopNFakeClient(BitmapFakeClient):
    pass


class TestTopNPushdownUnit:
    def _setup(self, holder, fn):
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("f")
        for col in (1, 2, 3):
            f.set_bit("standard", 1, col)
        f.set_bit("standard", 2, 4)
        idx.set_remote_max_slice(2)
        cluster = new_cluster(["local", "remotehost"], replica_n=1)
        client = TopNFakeClient(fn)
        e = Executor(holder, host="local", cluster=cluster,
                     client=client, use_mesh=False)
        local_slices = [s for s in range(3)
                        if cluster.fragment_nodes("i", s)[0].host
                        == "local"]
        return e, client, local_slices

    def test_pushdown_merge_and_missing_row_refetch(self, holder):
        refetched = []

        def fn2(node, index, query, slices):
            if "pushdown=true" in query:
                return [[Pair(1, 10), Pair(30, 7)]]
            assert "ids=" in query, f"unexpected leg: {query}"
            refetched.append(query)
            return [[Pair(2, 5)]]  # row 2's count on the remote node

        e, client, local_slices = self._setup(holder, fn2)
        res = e.execute("i", "TopN(frame=f, n=5)")[0]
        assert any("pushdown=true" in c[2] for c in client.calls)
        got = {p.id: p.count for p in res}
        if 0 in local_slices:
            # Local partial {1:3, 2:1}; remote {1:10, 30:7}; remote
            # refetch fills row 2 (+5).
            assert refetched and all("pushdown" not in q
                                     for q in refetched)
            assert got == {1: 13, 2: 6, 30: 7}
        else:
            # Data slice lives remotely: local partials are empty and
            # local refetches contribute nothing.
            assert got[30] == 7
        assert obs_metrics.TOPN_PUSHDOWN.labels("merged").value >= 1

    def test_pushdown_failure_degrades_to_fanout(self, holder):
        from pilosa_tpu.cluster.client import ClientError

        def fn(node, index, query, slices):
            if "pushdown=true" in query:
                raise ClientError("injected")
            if "ids=" in query:
                return [[Pair(1, 4)]]
            return [[Pair(1, 4)]]

        e, client, local_slices = self._setup(holder, fn)
        before = obs_metrics.TOPN_PUSHDOWN.labels("fallback").value
        res = e.execute("i", "TopN(frame=f, n=5)")[0]
        got = {p.id: p.count for p in res}
        assert got.get(1, 0) >= 4  # remote contribution survived
        assert obs_metrics.TOPN_PUSHDOWN.labels("fallback").value \
            == before + 1

    def test_remote_leg_answers_exact_untrimmed_partials(self, holder):
        """The pushdown leg contract: a remote=True query carrying
        pushdown=true returns EXACT counts over the node's own slices
        for every per-slice candidate — untrimmed."""
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("f")
        for row, n_bits in ((1, 5), (2, 4), (3, 3), (4, 2), (5, 1)):
            for col in range(n_bits):
                f.set_bit("standard", row, col)
        e = Executor(holder, host="local", use_mesh=False)
        res = e.execute("i", "TopN(frame=f, n=2, pushdown=true)",
                        slices=[0], opt=ExecOptions(remote=True))[0]
        # n=2 would trim to 2; the pushdown partial keeps every
        # candidate of the per-slice trim... which for ONE slice is
        # the per-slice top-2.
        got = {p.id: p.count for p in res}
        assert got == {1: 5, 2: 4}


# ---------------------------------------------------------------------------
# coordinator hot-query result cache (unit, scripted transport)


class ClusterCacheClient:
    """Scripted transport whose responses carry generation tokens
    (applied straight to the shared map, like the real pooled client)
    and which answers the /generations validation probe."""

    generation_aware = True

    def __init__(self, gens, tokens):
        self.gens = gens
        self.tokens = tokens  # host -> {slice: {fk: (uid, gen)}}
        self.exec_calls = []
        self.probe_calls = []

    def execute_query(self, node, index, query, slices, remote,
                      **kwargs):
        self.exec_calls.append((node.host, query, tuple(slices or ())))
        self.gens.apply(node.host, index,
                        {s: self.tokens[node.host][s]
                         for s in slices})
        return [7]

    def generations(self, index, slices=None, host=None,
                    deadline_s=None):
        self.probe_calls.append((host, tuple(slices or ())))
        t = {s: dict(self.tokens[host][s]) for s in (slices or [])}
        self.gens.apply(host, index, t)
        return t


class TestClusterResultCache:
    def test_hit_validate_invalidate_cycle(self, holder):
        must_set(holder, "i", "general", 10, 3)
        holder.index("i").set_remote_max_slice(2)
        cluster = new_cluster(["local", "remotehost"], replica_n=1)
        remote_slices = [s for s in range(3)
                         if cluster.fragment_nodes("i", s)[0].host
                         == "remotehost"]
        assert remote_slices
        gens = GenerationMap(staleness_s=60.0)
        tokens = {"remotehost": {s: {"general/standard": (50, 0)}
                                 for s in remote_slices}}
        client = ClusterCacheClient(gens, tokens)
        e = Executor(holder, host="local", cluster=cluster,
                     client=client, gens=gens, use_mesh=False)
        # Warm the map as a prior query's legs would have: a query
        # whose remote slices the map has NEVER seen stays uncached
        # (no pre-execution snapshot to attribute its results to).
        gens.apply("remotehost", "i",
                   {s: tokens["remotehost"][s] for s in remote_slices})
        q = 'Count(Bitmap(rowID=10, frame=general))'
        hits = obs_metrics.CLUSTER_CACHE_REQUESTS.labels("hit")
        inval = obs_metrics.CLUSTER_CACHE_REQUESTS.labels(
            "invalidated")
        h0, i0 = hits.value, inval.value

        r1 = e.execute("i", q)
        n_exec = len(client.exec_calls)
        assert n_exec >= 1 and not client.probe_calls
        # Identical repeat: ONE validation probe, zero execute legs.
        r2 = e.execute("i", q)
        assert r2 == r1
        assert len(client.exec_calls) == n_exec
        assert len(client.probe_calls) == 1
        assert hits.value == h0 + 1
        # A remote write bumps the owner's tokens: the next query
        # invalidates and recomputes — no stale answer.
        for s in remote_slices:
            tokens["remotehost"][s] = {"general/standard": (50, 9)}
        r3 = e.execute("i", q)
        assert r3 == r1  # scripted counts unchanged; path recomputed
        assert len(client.exec_calls) > n_exec
        assert inval.value == i0 + 1

    def test_local_write_invalidates_without_probe_mismatch(self,
                                                           holder):
        must_set(holder, "i", "general", 10, 3)
        holder.index("i").set_remote_max_slice(2)
        cluster = new_cluster(["local", "remotehost"], replica_n=1)
        remote_slices = [s for s in range(3)
                         if cluster.fragment_nodes("i", s)[0].host
                         == "remotehost"]
        local_slices = [s for s in range(3)
                        if s not in remote_slices]
        if not local_slices:
            pytest.skip("jump-hash gave every slice to the peer")
        gens = GenerationMap(staleness_s=60.0)
        tokens = {"remotehost": {s: {"general/standard": (50, 0)}
                                 for s in remote_slices}}
        client = ClusterCacheClient(gens, tokens)
        e = Executor(holder, host="local", cluster=cluster,
                     client=client, gens=gens, use_mesh=False)
        gens.apply("remotehost", "i",
                   {s: tokens["remotehost"][s] for s in remote_slices})
        q = 'Count(Bitmap(rowID=10, frame=general))'
        r1 = e.execute("i", q)
        n_exec = len(client.exec_calls)
        # Local write: the LOCAL token check catches it (no probe
        # round-trip needed to invalidate).
        holder.frame("i", "general").set_bit(
            "standard", 10, local_slices[0] * SLICE_WIDTH + 9)
        r2 = e.execute("i", q)
        assert r2[0] == r1[0] + 1
        assert len(client.exec_calls) > n_exec

    def test_epoch_bump_never_validates_stale_entries(self, holder):
        """ISSUE 12 satellite regression: after an elastic-resize
        epoch flip moves a slice to a NEW peer, a cluster-cache entry
        cached under the OLD owner's tokens must never validate — the
        old owner's copy freezes (it stops receiving writes), so its
        /generations probe would match forever. Both defenses are
        exercised: the placement epoch baked into the key (post-flip
        lookups can't even find the old entry) and the eager
        on_resize_change flush for moved slices."""
        must_set(holder, "i", "general", 10, 3)
        holder.index("i").set_remote_max_slice(2)
        cluster = new_cluster(["local", "remotehost"], replica_n=1)
        remote_slices = [s for s in range(3)
                         if cluster.fragment_nodes("i", s)[0].host
                         == "remotehost"]
        assert remote_slices
        gens = GenerationMap(staleness_s=60.0)
        tokens = {"remotehost": {s: {"general/standard": (50, 0)}
                                 for s in remote_slices},
                  # The post-flip owner: fresh uid, per the satellite.
                  "new:1": {s: {"general/standard": (77, 0)}
                            for s in range(3)}}
        client = ClusterCacheClient(gens, tokens)
        e = Executor(holder, host="local", cluster=cluster,
                     client=client, gens=gens, use_mesh=False)
        gens.apply("remotehost", "i",
                   {s: tokens["remotehost"][s] for s in remote_slices})
        q = 'Count(Bitmap(rowID=10, frame=general))'
        e.execute("i", q)
        n_exec = len(client.exec_calls)
        assert e._cluster_cache, "warm-up did not cache"
        old_key = next(iter(e._cluster_cache))
        assert old_key[-1] == 0  # epoch in the key
        # The resize moves ownership; the server calls
        # on_resize_change at install and flip (server.py
        # _apply_resize_message).
        cluster.install_resize("r1",
                               ["local", "remotehost", "new:1"])
        e.on_resize_change()
        # While the resize is in flight NOTHING caches.
        assert e._cluster_cache_key(
            "i", parse_pql(q), [0, 1, 2], ExecOptions()) is None
        cluster.flip_epoch("r1")
        e.on_resize_change(lambda index, s: True)  # all slices moved
        cluster.finalize_resize("r1", grace_s=0.0)
        # The eager flush dropped the entry outright...
        assert not e._cluster_cache
        # ...and even a hypothetical survivor could not serve: the
        # next query keys under epoch 1 and recomputes (the scripted
        # old owner would happily validate its frozen tokens — that
        # answer must never be served).
        hits = obs_metrics.CLUSTER_CACHE_REQUESTS.labels("hit").value
        e.execute("i", q)
        assert len(client.exec_calls) > n_exec, \
            "stale cluster-cache entry served after the epoch flip"
        assert obs_metrics.CLUSTER_CACHE_REQUESTS.labels(
            "hit").value == hits

    def test_write_queries_and_partial_are_never_cached(self, holder):
        must_set(holder, "i", "general", 10, 3)
        holder.index("i").set_remote_max_slice(2)
        cluster = new_cluster(["local", "remotehost"], replica_n=1)
        gens = GenerationMap()
        client = ClusterCacheClient(gens, {"remotehost": {}})
        e = Executor(holder, host="local", cluster=cluster,
                     client=client, gens=gens, use_mesh=False)
        q = parse_pql('SetBit(frame="general", rowID=1, columnID=1)')
        assert e._cluster_cache_key("i", q, [0, 1, 2],
                                    ExecOptions()) is None
        rq = parse_pql('Count(Bitmap(rowID=1, frame=general))')
        assert e._cluster_cache_key(
            "i", rq, [0, 1, 2], ExecOptions(partial=True)) is None
        assert e._cluster_cache_key(
            "i", rq, [0, 1, 2], ExecOptions(remote=True)) is None
        assert e._cluster_cache_key("i", rq, [0, 1, 2],
                                    ExecOptions()) is not None


# ---------------------------------------------------------------------------
# REAL 2-node gossip cluster + single-node reference (the acceptance leg)


def _post(host: str, path: str, body: bytes) -> bytes:
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=30).read()


def _query(host: str, index: str, body: str, qs: str = ""):
    req = urllib.request.Request(
        f"http://{host}/index/{index}/query{qs}",
        data=body.encode(), method="POST")
    resp = urllib.request.urlopen(req, timeout=30)
    return json.loads(resp.read())["results"], dict(resp.headers)


def _metric(host: str, name: str, **labels) -> float:
    with urllib.request.urlopen(f"http://{host}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    want = "".join(sorted(f'{k}="{v}"' for k, v in labels.items()))
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue
        if labels:
            inside = rest[1:rest.index("}")] if rest[0] == "{" else ""
            if "".join(sorted(inside.split(","))) != want:
                continue
        total += float(line.rsplit(" ", 1)[1])
    return total


def _topn(host: str, index: str, n: int):
    res, _ = _query(host, index, f'TopN(frame="f", n={n})')
    return [(p["key"] if "key" in p else p["id"], p["count"])
            for p in res[0]]


def test_two_node_distributed_fastpath(tmp_path):
    pa, pb, ps = free_port(), free_port(), free_port()
    ga, gb = free_port(), free_port()
    hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    procs = []
    logs = []

    def spawn(name, port, internal=None, seed="", cluster=True):
        d = tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        log = open(tmp_path / f"{name}.log", "a")
        logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--anti-entropy.interval", "300s"]
        if cluster:
            argv += ["--cluster.type", "gossip",
                     "--cluster.hosts", hosts,
                     "--cluster.replicas", "1",
                     "--cluster.internal-port", str(internal)]
            if seed:
                argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        procs.append(p)
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    try:
        host_a = spawn("a", pa, ga)
        host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
        host_s = spawn("solo", ps, cluster=False)

        for h in (host_a, host_s):
            _post(h, "/index/df", b"{}")
            _post(h, "/index/df/frame/f", b"{}")

        from pilosa_tpu.cluster.client import Client
        rng = np.random.default_rng(23)
        n_cols = 4 * SLICE_WIDTH
        rows = rng.integers(0, 8, 600).astype(np.uint64)
        cols = rng.choice(n_cols, size=600,
                          replace=False).astype(np.uint64)
        Client(host_a).import_arrays("df", "f", rows, cols)
        Client(host_s).import_arrays("df", "f", rows, cols)
        model: dict[int, set] = {}
        for r, c in zip(rows.tolist(), cols.tolist()):
            model.setdefault(r, set()).add(c)

        # Both cluster nodes own SOME slices (replicas=1 over 4
        # slices), and cross-node slice discovery has converged.
        def row_count(h, row):
            res, _ = _query(h, "df",
                            f'Count(Bitmap(frame="f", rowID={row}))')
            return res[0]

        want0 = len(model[0])
        deadline = time.time() + 20
        while time.time() < deadline:
            if (row_count(host_a, 0) == want0
                    and row_count(host_b, 0) == want0):
                break
            time.sleep(0.3)
        assert row_count(host_a, 0) == want0
        assert row_count(host_b, 0) == want0

        # (a) distributed TopN == single-node, randomized workload,
        # several n, from BOTH coordinators.
        for k in (2, 3, 5, 8):
            want = _topn(host_s, "df", k)
            assert _topn(host_a, "df", k) == want, f"n={k} via A"
            assert _topn(host_b, "df", k) == want, f"n={k} via B"
        assert _metric(host_a,
                       "pilosa_executor_topn_pushdown_total",
                       outcome="merged") >= 1
        assert _metric(host_b,
                       "pilosa_executor_topn_pushdown_total",
                       outcome="merged") >= 1

        # (b) repeated resident chain: second identical query is a
        # generation-validated cluster-cache hit; a write through the
        # OTHER node invalidates it on the very next query.
        q = ('Count(Intersect(Bitmap(frame="f", rowID=0),'
             ' Bitmap(frame="f", rowID=1)))')
        want_ix = len(model[0] & model[1])
        r1, _ = _query(host_a, "df", q)
        assert r1[0] == want_ix
        hits0 = _metric(host_a,
                        "pilosa_executor_cluster_cache_requests_total",
                        outcome="hit")
        r2, _ = _query(host_a, "df", q)
        assert r2[0] == want_ix
        assert _metric(
            host_a, "pilosa_executor_cluster_cache_requests_total",
            outcome="hit") == hits0 + 1
        # Write through B: make a column shared between rows 0 and 1.
        new_col = next(c for c in sorted(model[1])
                       if c not in model[0])
        _query(host_b, "df",
               f'SetBit(frame="f", rowID=0, columnID={new_col})')
        _query(host_s, "df",
               f'SetBit(frame="f", rowID=0, columnID={new_col})')
        model[0].add(new_col)
        r3, _ = _query(host_a, "df", q)
        assert r3[0] == len(model[0] & model[1]) == want_ix + 1, \
            "stale answer after a write through the other node"

        # (c) chaos: an injected rpc.recv failure (both attempts — a
        # single error is absorbed by the client's idempotent
        # keep-alive retry) downgrades the pushdown to the fan-out
        # path with a CORRECT answer; a full partition with
        # ?partial=1 reports the missing slices instead of answering
        # wrong.
        fb0 = _metric(host_a, "pilosa_executor_topn_pushdown_total",
                      outcome="fallback")
        _post(host_a, "/debug/failpoints",
              json.dumps({"site": "rpc.recv",
                          "spec": "error*2"}).encode())
        assert _topn(host_a, "df", 4) == _topn(host_s, "df", 4)
        assert _metric(host_a, "pilosa_executor_topn_pushdown_total",
                       outcome="fallback") == fb0 + 1
        _post(host_a, "/debug/failpoints",
              json.dumps({"site": "rpc.recv", "spec": "error"}).encode())
        res, headers = _query(host_a, "df",
                              'TopN(frame="f", n=8)', qs="?partial=1")
        assert "X-Pilosa-Partial" in headers
        solo = dict(_topn(host_s, "df", 8))
        for p in res[0]:
            rid = p.get("id", p.get("key"))
            assert p["count"] <= solo.get(rid, 0), \
                "partial degraded answer exceeded the true count"
        _post(host_a, "/debug/failpoints",
              json.dumps({"site": "rpc.recv", "spec": "off"}).encode())
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
