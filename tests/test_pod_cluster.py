"""Cluster-of-pods end-to-end: a plain node + a 2-process CPU pod as
the two cluster nodes (BASELINE config 5's shape, single-host form).

Every query enters through the plain node: cluster map-reduce forwards
the pod's slices to the coordinator over HTTP, which serves them
pod-wide (collectives for Count/TopN exact, podLocal legs for
materialization) — the full three-process composition of
executor map-reduce × pod broadcast.
"""

import os
import sys

from podenv import ChildSet, cpu_env, free_port, pod_env

_HERE = os.path.dirname(os.path.abspath(__file__))


def test_cluster_of_plain_node_and_pod(tmp_path):
    jax_port = free_port()
    host_a = f"localhost:{free_port()}"
    pod_peers = [f"localhost:{free_port()}", f"localhost:{free_port()}"]
    script = os.path.join(_HERE, "pod_cluster_child.py")

    def env_for(role):
        if role == "a":
            env = cpu_env()
            env["PILOSA_TPU_MESH"] = "0"  # plain host-path node
        else:
            env = pod_env(0 if role == "b0" else 1, jax_port, pod_peers)
        env["POD_CLUSTER_A"] = host_a
        env["POD_CLUSTER_B0"] = pod_peers[0]
        return env

    children = ChildSet(tmp_path)
    try:
        for role in ("b0", "b1", "a"):
            data_dir = tmp_path / role
            data_dir.mkdir()
            children.spawn(
                role, [sys.executable, script, role, str(data_dir)],
                env_for(role), pipe=(role == "a"))
        out, err = children.procs["a"].communicate(timeout=240)
        assert children.procs["a"].returncode == 0, (
            f"node A failed rc={children.procs['a'].returncode}\n"
            f"stdout:\n{out}\nstderr:\n{err[-4000:]}\n"
            f"{children.logs_tail()}")
        assert "POD_CLUSTER_OK" in out, out
    finally:
        children.cleanup()
