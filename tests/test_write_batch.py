"""Batched write engine: native batch_add/batch_remove crossings, WAL
group commit, frozen-capture COW, and the executor SetBit/ClearBit
batch run (reference per-op loop: fragment.go:369-459,
executor.go:664-797 — the batch path must be observationally
identical)."""

import fcntl
import io
import os
import tempfile

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.models.frame import FrameOptions
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.storage import native, roaring
from pilosa_tpu.storage.fragment import Fragment


def _rand_vals(rng, n=30000):
    sparse = rng.integers(0, 1 << 24, n).astype(np.uint64)
    dense = (np.uint64(7 << 16)
             + rng.integers(0, 60000, n // 2).astype(np.uint64))
    return np.concatenate([sparse, dense])


class TestApplyBatch:
    def test_add_remove_parity_with_per_op(self):
        rng = np.random.default_rng(3)
        for _ in range(3):
            vals = _rand_vals(rng)
            ref = roaring.Bitmap()
            for v in vals.tolist():
                ref._add(v)
            b = roaring.Bitmap()
            for s in range(0, len(vals), 1000):
                b.apply_batch(vals[s:s + 1000], set=True, wal=False)
            assert np.array_equal(ref.values(), b.values())
            b.check()
            rem = np.concatenate(
                [vals[::2],
                 rng.integers(0, 1 << 24, 5000).astype(np.uint64)])
            for v in rem.tolist():
                ref._remove(v)
            for s in range(0, len(rem), 1000):
                b.apply_batch(rem[s:s + 1000], set=False, wal=False)
            assert np.array_equal(ref.values(), b.values())
            b.check()

    def test_wal_group_commit_replays(self):
        rng = np.random.default_rng(5)
        buf = io.BytesIO()
        b = roaring.Bitmap()
        b.write_to(buf)
        b.op_writer = buf
        vals = rng.integers(0, 1 << 22, 5000).astype(np.uint64)
        ch = b.apply_batch(vals, set=True, wal=True)
        ch2 = b.apply_batch(vals[::3], set=False, wal=True)
        assert b.op_n == len(ch) + len(ch2)
        b.op_writer = None
        loaded = roaring.Bitmap.unmarshal(buf.getvalue())
        assert np.array_equal(loaded.values(), b.values())

    def test_wal_records_byte_identical_to_scalar(self):
        vals = np.array([0, 7, 1 << 33, (1 << 63) + 5], dtype=np.uint64)
        blob = roaring._wal_blob(vals, roaring.OP_ADD)
        for i, v in enumerate(vals.tolist()):
            assert blob[i * 13:(i + 1) * 13] == \
                roaring.Op(roaring.OP_ADD, v).marshal()

    def test_changed_excludes_idempotent_resets(self):
        b = roaring.Bitmap()
        first = b.apply_batch(np.array([1, 2, 3], dtype=np.uint64),
                              wal=False)
        assert len(first) == 3
        again = b.apply_batch(np.array([2, 3, 4], dtype=np.uint64),
                              wal=False)
        assert again.tolist() == [4]
        gone = b.apply_batch(np.array([3, 99], dtype=np.uint64),
                             set=False, wal=False)
        assert gone.tolist() == [3]

    def test_array_bitmap_conversions_both_ways(self):
        b = roaring.Bitmap()
        # fill one container past ARRAY_MAX_SIZE in two batches
        b.apply_batch(np.arange(3000, dtype=np.uint64), wal=False)
        assert b.containers[0].is_array()
        b.apply_batch(np.arange(3000, 6000, dtype=np.uint64), wal=False)
        assert not b.containers[0].is_array()
        b.check()
        # remove back below the boundary: container must unpack
        b.apply_batch(np.arange(4000, 6000, dtype=np.uint64),
                      set=False, wal=False)
        assert b.containers[0].is_array()
        assert b.count() == 4000
        b.check()

    def test_frozen_capture_is_immutable_under_writes(self):
        rng = np.random.default_rng(11)
        b = roaring.Bitmap()
        b.apply_batch(rng.integers(0, 1 << 22, 50000).astype(np.uint64),
                      wal=False)
        want = b.values().copy()
        frozen = b.freeze()
        # batch, bulk, and point mutations all land after the capture
        b.apply_batch(rng.integers(0, 1 << 22, 50000).astype(np.uint64),
                      wal=False)
        b.add_many(rng.integers(0, 1 << 22, 1000).astype(np.uint64))
        for v in rng.integers(0, 1 << 22, 200).tolist():
            b._add(int(v))
            b._remove(int(rng.integers(0, 1 << 22)))
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "snap")
            with open(p, "wb") as f:
                roaring.write_frozen(frozen, f)
            loaded = roaring.Bitmap.unmarshal(open(p, "rb").read())
            loaded.check()
            assert np.array_equal(loaded.values(), want)

    def test_write_frozen_bytesio_fallback_matches_native(self):
        rng = np.random.default_rng(13)
        b = roaring.Bitmap()
        b.apply_batch(_rand_vals(rng, 20000), wal=False)
        frozen = b.freeze()
        buf = io.BytesIO()
        roaring.write_frozen(frozen, buf)  # non-fd target: Python path
        loaded = roaring.Bitmap.unmarshal(buf.getvalue())
        assert np.array_equal(loaded.values(), b.values())
        if native.available():
            with tempfile.TemporaryDirectory() as d:
                p = os.path.join(d, "snap")
                with open(p, "wb") as f:
                    roaring.write_frozen(b.freeze(), f)
                assert open(p, "rb").read() == buf.getvalue()

    def test_fallback_python_groups_match_native(self):
        rng = np.random.default_rng(17)
        vals = _rand_vals(rng, 15000)
        via_native = roaring.Bitmap()
        via_python = roaring.Bitmap()
        for s in range(0, len(vals), 900):
            chunk = vals[s:s + 900]
            via_native.apply_batch(chunk, wal=False)
            # force the fallback path regardless of toolchain
            highs = np.sort(np.unique(chunk)) >> np.uint64(16)
            srt = np.sort(np.unique(chunk))
            bounds = np.flatnonzero(highs[1:] != highs[:-1]) + 1
            starts = np.concatenate(([0], bounds, [len(srt)]))
            gk = highs[starts[:-1]]
            keys_np = via_python._keys_np()
            missing = gk[~np.isin(gk, keys_np)]
            if len(missing):
                via_python._insert_containers(missing.tolist())
            idx = np.searchsorted(via_python._keys_np(), gk)
            conts = [via_python.containers[i] for i in idx.tolist()]
            via_python._apply_groups_python(
                conts, gk, (srt & np.uint64(0xFFFF)).astype(np.uint32),
                starts, True, False)
        assert np.array_equal(via_native.values(), via_python.values())


class TestFragmentBatch:
    def test_batch_matches_per_op_fragment(self):
        rng = np.random.default_rng(9)
        n = 20000
        rows = rng.integers(0, 200, n).astype(np.uint64)
        cols = rng.integers(0, 1 << 20, n).astype(np.uint64)
        with tempfile.TemporaryDirectory() as d:
            fa = Fragment(os.path.join(d, "a"), "i", "f", "standard", 0)
            fb = Fragment(os.path.join(d, "b"), "i", "f", "standard", 0)
            fa.open()
            fb.open()
            for r, c in zip(rows.tolist(), cols.tolist()):
                fa.set_bit(r, c)
            for s in range(0, n, 700):
                fb.set_bits(rows[s:s + 700], cols[s:s + 700])
            fa._join_snapshot()
            fb._join_snapshot()
            assert np.array_equal(fa.storage.values(),
                                  fb.storage.values())
            for rid in np.unique(rows)[:40].tolist():
                assert fa.row_count(rid) == fb.row_count(rid)
                assert fa.cache.get(rid) == fb.cache.get(rid)
            fb.clear_bits(rows[::3], cols[::3])
            for r, c in zip(rows[::3].tolist(), cols[::3].tolist()):
                fa.clear_bit(r, c)
            fa._join_snapshot()
            fb._join_snapshot()
            assert np.array_equal(fa.storage.values(),
                                  fb.storage.values())
            fa.close()
            fb.close()

    def test_batch_survives_crash_reopen(self):
        """Kill the file mid-life: batch-written WAL records replay
        identically on reopen (snapshot + tail)."""
        rng = np.random.default_rng(21)
        n = 30000
        rows = rng.integers(0, 300, n).astype(np.uint64)
        cols = rng.integers(0, 1 << 20, n).astype(np.uint64)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "frag")
            frag = Fragment(p, "i", "f", "standard", 0)
            frag.open()
            for s in range(0, n, 1000):
                frag.set_bits(rows[s:s + 1000], cols[s:s + 1000])
            frag._join_snapshot()
            frag.wal_barrier()  # the ack point: records reach the OS
            want = frag.storage.values().copy()
            # simulate crash: no close(), just drop and reopen. A real
            # crash releases the flock with the process; in-process the
            # mmap holds a dup of the locked description, so release
            # explicitly.
            if frag._wal is not None:
                frag._wal.close()
            frag.storage.op_writer = None
            fcntl.flock(frag._file.fileno(), fcntl.LOCK_UN)
            frag._file.close()
            frag2 = Fragment(p, "i", "f", "standard", 0)
            frag2.__init__(p, "i", "f", "standard", 0)
            frag2.open()
            assert np.array_equal(frag2.storage.values(), want)
            frag2.storage.check()
            frag2.close()

    def test_torn_batch_tail_trimmed(self):
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "frag")
            frag = Fragment(p, "i", "f", "standard", 0)
            frag.open()
            frag.set_bits(np.arange(100, dtype=np.uint64),
                          np.arange(100, dtype=np.uint64) * 7)
            frag._join_snapshot()
            want = frag.storage.count()
            frag.close()
            # tear the last record mid-write
            with open(p, "ab") as f:
                f.write(roaring.Op(roaring.OP_ADD, 12345).marshal()[:7])
            frag2 = Fragment(p, "i", "f", "standard", 0)
            frag2.open()
            assert frag2.storage.count() == want
            frag2.close()

    def test_duplicate_ops_report_first_only(self):
        with tempfile.TemporaryDirectory() as d:
            h = Holder(d)
            h.open()
            frame = h.create_index("i").create_frame("f")
            changed = frame.mutate_bits(
                "standard",
                np.array([1, 1, 2], dtype=np.uint64),
                np.array([5, 5, 9], dtype=np.uint64), True)
            assert changed.tolist() == [True, False, True]
            h.close()


class TestExecutorMutateBatch:
    def _run(self, qcalls, inverse=False):
        outs = []
        for batched in (False, True):
            with tempfile.TemporaryDirectory() as d:
                h = Holder(d)
                h.open()
                frame = h.create_index("i").create_frame(
                    "f", FrameOptions(inverse_enabled=inverse))
                ex = Executor(h, host="local", use_mesh=False)
                if batched:
                    res = ex.execute("i", "\n".join(qcalls))
                else:
                    res = []
                    for q in qcalls:
                        res.extend(ex.execute("i", q))
                views = {}
                for vname in (["standard", "inverse"] if inverse
                              else ["standard"]):
                    v = frame.view(vname)
                    if v:
                        views[vname] = {
                            s: f.storage.values().copy()
                            for s, f in v.fragments.items()}
                outs.append((res, views))
                ex.close()
                h.close()
        (res_a, views_a), (res_b, views_b) = outs
        assert res_a == res_b
        assert views_a.keys() == views_b.keys()
        for vname in views_a:
            assert views_a[vname].keys() == views_b[vname].keys()
            for s in views_a[vname]:
                assert np.array_equal(views_a[vname][s],
                                      views_b[vname][s])

    def test_setbit_run_parity(self):
        import random
        random.seed(4)
        calls = [f'SetBit(frame="f", rowID={random.randrange(40)},'
                 f' columnID={random.randrange(1 << 21)})'
                 for _ in range(300)]
        calls += calls[:15]  # duplicates: only the first changes
        self._run(calls)

    def test_setbit_run_parity_inverse(self):
        import random
        random.seed(7)
        calls = [f'SetBit(frame="f", rowID={random.randrange(40)},'
                 f' columnID={random.randrange(1 << 21)})'
                 for _ in range(200)]
        self._run(calls, inverse=True)

    def test_mixed_runs_and_reads(self):
        """Batch runs interleave with reads and short runs; results
        stay positionally aligned."""
        with tempfile.TemporaryDirectory() as d:
            h = Holder(d)
            h.open()
            h.create_index("i").create_frame("f")
            ex = Executor(h, host="local", use_mesh=False)
            sets = "\n".join(
                f'SetBit(frame="f", rowID=1, columnID={c})'
                for c in range(20))
            q = (sets + '\nCount(Bitmap(frame="f", rowID=1))\n'
                 'SetBit(frame="f", rowID=1, columnID=3)')
            res = ex.execute("i", q)
            assert res[:20] == [True] * 20
            assert res[20] == 20
            assert res[21] is False  # idempotent re-set
            ex.close()
            h.close()

    def test_timestamped_calls_fall_back(self):
        """Timestamped SetBits never enter the batch run (time-view
        fan-out is per-op) but still work mid-stream."""
        with tempfile.TemporaryDirectory() as d:
            h = Holder(d)
            h.open()
            idx = h.create_index("i")
            idx.create_frame("f", FrameOptions(time_quantum="YMD"))
            ex = Executor(h, host="local", use_mesh=False)
            calls = ["\n".join(
                f'SetBit(frame="f", rowID=1, columnID={c},'
                f' timestamp="2017-01-0{1 + c % 3}T00:00")'
                for c in range(9))]
            res = ex.execute("i", calls[0])
            assert res == [True] * 9
            assert idx.frame("f").view("standard_2017") is not None
            ex.close()
            h.close()


class TestFastParse:
    def test_fast_and_full_agree(self):
        from pilosa_tpu.pql.parser import Parser, parse
        cases = [
            'SetBit(frame="f", rowID=3, columnID=77)',
            'TopN(frame="f", n=5)',
            'Bitmap(frame=\'x-y.z\', rowID=0)'
            'Count(Bitmap(frame="a", rowID=1))',
            'SetBit(frame="f", rowID=1, columnID=2,'
            ' timestamp="2017-01-02T15:04")',
            'Union(Bitmap(frame="a", rowID=1), Bitmap(frame="a",'
            ' rowID=2))',
            'TopN(frame="f", n=2, ids=[1,2,3])',
            'SetRowAttrs(frame="f", rowID=1, x=true, y=null, z=1.5)',
            'Count()',
            '',
        ]
        for c in cases:
            assert str(parse(c)) == str(Parser(c).parse()), c

    def test_fast_rejects_what_full_rejects(self):
        from pilosa_tpu.errors import PilosaError
        from pilosa_tpu.pql.parser import parse
        for bad in ('SetBit(frame="f", frame="g")',   # duplicate key
                    'SetBit(rowID=99999999999999999999)',  # > int64
                    'SetBit(frame="f"'):              # unterminated
            with pytest.raises(PilosaError):
                parse(bad)


class TestCacheCompletenessAfterCrash:
    def test_single_pass_topn_correct_after_sigkill_style_recovery(self):
        """Rows written after the last cache-sidecar flush exist only
        in the WAL; after a crash-style reopen the count cache must be
        repaired (or flagged incomplete) so TopN never under-ranks
        them (review r5 on the single-pass leg)."""
        import numpy as np

        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder

        with tempfile.TemporaryDirectory() as d:
            h = Holder(d)
            h.open()
            frame = h.create_index("i").create_frame("f")
            frame.import_bits([1] * 50, list(range(50)))
            h.close()  # flushes the cache sidecar

            h2 = Holder(d)
            h2.open()
            frame2 = h2.frame("i", "f")
            # New dominant row via WAL'd writes, then crash: drop
            # without close() so the sidecar never learns about it
            # (explicit flock release stands in for process death).
            for c in range(80):
                frame2.set_bit("standard", 7, c)
            frag = frame2.view("standard").fragments[0]
            frag._join_snapshot()
            frag.wal_barrier()  # the ack point: records reach the OS
            frag.storage.op_writer = None
            import fcntl
            fcntl.flock(frag._file.fileno(), fcntl.LOCK_UN)
            frag._file.close()

            h3 = Holder(d)
            h3.open()
            ex = Executor(h3, host="local", use_mesh=False)
            pairs = ex.execute("i", "TopN(frame=f, n=2)")[0]
            ids = [(p.id, p.count) for p in pairs]
            assert ids[0] == (7, 80), ids  # WAL-only row ranked first
            assert ids[1] == (1, 50), ids
            ex.close()
            h3.close()


class TestTableDirtyPatching:
    def test_interleaved_point_batch_freeze_conversions(self):
        """Point mutations interleaved with batches, freezes, and
        container conversions (array<->bitmap boundary crossings): the
        serialization table must never serve stale types/pointers to
        the batch engine or to frozen captures."""
        rng = np.random.default_rng(42)
        ref = roaring.Bitmap()
        b = roaring.Bitmap()
        snaps = []
        for rounds in range(30):
            # batch adds clustered into few containers (drives some
            # past the 4096 array boundary over time)
            chunk = (np.uint64((rounds % 4) << 16)
                     + rng.integers(0, 50000, 600).astype(np.uint64))
            b.apply_batch(chunk, set=True, wal=False)
            for v in chunk.tolist():
                ref._add(v)
            # point ops on the SAME containers (stale-entry hazard)
            for _ in range(20):
                v = int((rounds % 4) << 16) + int(rng.integers(0, 50000))
                b._add(v)
                ref._add(v)
            v = int((rounds % 4) << 16) + int(rng.integers(0, 50000))
            b._remove(v)
            ref._remove(v)
            # freeze mid-stream; serialize later and compare
            if rounds % 3 == 0:
                snaps.append((b.freeze(), b.count()))
            # batch removes
            rm = chunk[::5]
            b.apply_batch(rm, set=False, wal=False)
            for v in rm.tolist():
                ref._remove(v)
        assert np.array_equal(ref.values(), b.values())
        b.check()
        with tempfile.TemporaryDirectory() as d:
            for k, (fr, cnt) in enumerate(snaps):
                p = os.path.join(d, f"s{k}")
                with open(p, "wb") as f:
                    roaring.write_frozen(fr, f)
                loaded = roaring.Bitmap.unmarshal(open(p, "rb").read())
                loaded.check()
                assert loaded.count() == cnt, k
