"""Cluster topology tests (reference cluster_test.go)."""

from pilosa_tpu.cluster.topology import (Cluster, Node, fnv1a_64, jump_hash,
                                         new_cluster)


class TestJumpHash:
    def test_range_and_determinism(self):
        for n in (1, 3, 16, 1024):
            buckets = [jump_hash(k, n) for k in range(200)]
            assert all(0 <= b < n for b in buckets)
            assert buckets == [jump_hash(k, n) for k in range(200)]

    def test_monotone_consistency(self):
        # Jump hash guarantee: growing n only moves keys INTO the new
        # bucket, never between existing buckets.
        for k in range(500):
            a, b = jump_hash(k, 7), jump_hash(k, 8)
            assert a == b or b == 7

    def test_distribution(self):
        n = 8
        counts = [0] * n
        for k in range(8000):
            counts[jump_hash(k, n)] += 1
        assert min(counts) > 600  # roughly uniform (expected 1000)


class TestFNV:
    def test_known_vectors(self):
        # Standard FNV-1a 64 test vectors.
        assert fnv1a_64(b"") == 0xCBF29CE484222325
        assert fnv1a_64(b"a") == 0xAF63DC4C8601EC8C
        assert fnv1a_64(b"foobar") == 0x85944171F73967E8


class TestCluster:
    def test_partition_stable_and_in_range(self):
        c = new_cluster(["host0", "host1", "host2"])
        for s in range(100):
            p = c.partition("i", s)
            assert 0 <= p < c.partition_n
            assert p == c.partition("i", s)
        # Different index names partition differently somewhere.
        assert any(c.partition("i", s) != c.partition("j", s)
                   for s in range(100))

    def test_fragment_nodes_replicas(self):
        c = new_cluster(["host0", "host1", "host2"], replica_n=2)
        owners = c.fragment_nodes("i", 0)
        assert len(owners) == 2
        assert len({n.host for n in owners}) == 2
        # Replicas are ring successors (cluster.go:220-240).
        i0 = c.nodes.index(owners[0])
        assert owners[1] is c.nodes[(i0 + 1) % 3]

    def test_replica_capped_by_cluster_size(self):
        c = new_cluster(["a", "b"], replica_n=5)
        assert len(c.fragment_nodes("i", 3)) == 2

    def test_owns_fragment_and_slices(self):
        c = new_cluster(["host0", "host1", "host2"])
        for s in range(50):
            owners = {n.host for n in c.fragment_nodes("i", s)}
            for h in ("host0", "host1", "host2"):
                assert c.owns_fragment(h, "i", s) == (h in owners)
        all_owned = sorted(
            s for h in ("host0", "host1", "host2")
            for s in c.owns_slices("i", 49, h))
        assert all_owned == list(range(50))  # exact partition of slices

    def test_empty_cluster_owns_nothing(self):
        c = Cluster(nodes=[])
        assert c.owns_slices("i", 10, "h") == []
        assert c.fragment_nodes("i", 0) == []

    def test_single_node_owns_everything(self):
        c = new_cluster(["only"])
        for s in range(20):
            assert c.owns_fragment("only", "i", s)

    def test_node_states(self):
        class StaticSet:
            def __init__(self, nodes):
                self._nodes = nodes

            def nodes(self):
                return self._nodes

        c = Cluster(nodes=[Node("a"), Node("b")],
                    node_set=StaticSet([Node("a")]))
        assert c.node_states() == {"a": "UP", "b": "DOWN"}
